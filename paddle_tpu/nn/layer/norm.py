"""Normalization layers (ref: python/paddle/nn/layer/norm.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["LayerNorm", "RMSNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D",
           "BatchNorm3D", "GroupNorm", "InstanceNorm2D", "SyncBatchNorm",
           "LocalResponseNorm"]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            self.normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            self.normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}, epsilon={self.epsilon}"


class RMSNorm(Layer):
    """ref parity: paddle.incubate.nn.FusedRMSNorm / fused_rms_norm kernel."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features)))

    def forward(self, x):
        training = self.training and not (self.use_global_stats is True)
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=training,
                            momentum=self.momentum, epsilon=self.epsilon,
                            data_format=self.data_format)


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCW", use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """On TPU, batch stats sync comes from computing them on the global
    (sharded) batch under GSPMD — no separate cross-replica kernel needed
    (ref: paddle.nn.SyncBatchNorm wrapping c_sync ops)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.num_groups = num_groups
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight,
                            self.bias, self.data_format)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)
