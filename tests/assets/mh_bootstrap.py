"""Shared bootstrap for the launched multi-host workers: this process
simulates ONE host with 4 virtual CPU devices. MUST be imported before
jax (env flags bind at backend init); finishes with the rendezvous ->
jax.distributed bridge up and the global device view asserted."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=4"
    " --xla_cpu_collective_call_terminate_timeout_seconds=900"
    " --xla_cpu_collective_call_warn_stuck_timeout_seconds=300")

import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import paddle_tpu.distributed as dist  # noqa: E402

dist.init_parallel_env()

assert jax.process_count() == int(os.environ["PADDLE_TRAINERS_NUM"]), \
    (jax.process_count(), os.environ["PADDLE_TRAINERS_NUM"])
assert jax.device_count() == 4 * jax.process_count()
