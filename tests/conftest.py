"""Test configuration: run the suite on a simulated 8-device CPU mesh.

SURVEY §4.2 build lesson: the reference tests distributed logic single-host
(Gloo fake, subprocess ranks); the TPU-native equivalent is
xla_force_host_platform_device_count so sharding/collective tests execute a
real 8-way SPMD program without hardware. Must run before jax import.
"""

import os

# force CPU even though the session profile exports JAX_PLATFORMS=axon (the
# real chip): the 8-device simulated mesh only exists on the cpu platform
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
# 8 virtual devices share one physical core: a lagging device thread can
# miss XLA-CPU's default 40s collective rendezvous kill on a busy host


def _xla_knows(flag_name: str) -> bool:
    """True when the installed jaxlib's XLA recognizes `flag_name`. Older
    XLA builds hard-abort the process on any unknown flag in XLA_FLAGS
    (parse_flags_from_env), so probe the binary before opting in."""
    try:
        import mmap
        import jaxlib
        so = os.path.join(os.path.dirname(jaxlib.__file__),
                          "xla_extension.so")
        with open(so, "rb") as f:
            with mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ) as m:
                return m.find(flag_name.encode()) != -1
    except Exception:
        return False


if "xla_cpu_collective_call_terminate_timeout_seconds" not in flags and \
        _xla_knows("xla_cpu_collective_call_terminate_timeout_seconds"):
    flags += (" --xla_cpu_collective_call_terminate_timeout_seconds=900"
              " --xla_cpu_collective_call_warn_stuck_timeout_seconds=300")
os.environ["XLA_FLAGS"] = flags
# keep CI deterministic and quiet
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# env var alone loses to the preinstalled axon PJRT plugin in this image; the
# config update is authoritative
jax.config.update("jax_platforms", "cpu")

# numerics tests compare against f32 references; the TPU-idiomatic low default
# (bf16 MXU passes) is exercised explicitly by the kernel/perf tests instead
jax.config.update("jax_default_matmul_precision", "highest")

# persistent compilation cache: the suite is compile-bound; cached XLA
# executables cut full-suite time from ~20min to a few minutes on reruns
jax.config.update("jax_compilation_cache_dir", "/tmp/paddle_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


# ---------------------------------------------------------------------------
# global-state hygiene: tests that fleet.init() a hybrid mesh must not leak
# it into later tests (the ambient mesh changes eager-collective routing)
# ---------------------------------------------------------------------------
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _restore_global_mesh():
    from paddle_tpu.distributed.mesh import get_mesh, set_mesh
    from paddle_tpu.distributed import fleet
    prev = get_mesh()
    prev_fleet = dict(fleet._fleet_state)
    yield
    set_mesh(prev)
    fleet._fleet_state.clear()
    fleet._fleet_state.update(prev_fleet)
