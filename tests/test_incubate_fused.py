"""incubate fused-op block parity (SURVEY §2.1 fused kernels row:
fused attention / FFN / bias+dropout+residual+LN / masked MHA decode)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate.nn.functional as F

R = np.random.RandomState(4)


def _t(a, sg=True):
    return paddle.to_tensor(np.asarray(a, np.float32), stop_gradient=sg)


def test_fused_bias_dropout_residual_ln():
    x = R.randn(2, 5, 8).astype(np.float32)
    res = R.randn(2, 5, 8).astype(np.float32)
    b = R.randn(8).astype(np.float32)
    w = np.ones(8, np.float32)
    bias = np.zeros(8, np.float32)
    out = F.fused_bias_dropout_residual_layer_norm(
        _t(x), _t(res), bias=_t(b), ln_scale=_t(w), ln_bias=_t(bias),
        dropout_rate=0.0)
    h = x + b + res
    ref = (h - h.mean(-1, keepdims=True)) / np.sqrt(
        h.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_fused_feedforward_matches_composite():
    x = R.randn(2, 4, 8).astype(np.float32)
    w1 = R.randn(8, 16).astype(np.float32)
    w2 = R.randn(16, 8).astype(np.float32)
    ln_w = np.ones(8, np.float32)
    ln_b = np.zeros(8, np.float32)
    out = F.fused_feedforward(_t(x), _t(w1), _t(w2),
                              ln2_scale=_t(ln_w), ln2_bias=_t(ln_b),
                              dropout1_rate=0.0, dropout2_rate=0.0,
                              activation="relu")
    h = x + np.maximum(x @ w1, 0) @ w2
    ref = (h - h.mean(-1, keepdims=True)) / np.sqrt(
        h.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_fused_multi_head_attention_matches_sdpa():
    B, S, H, D = 2, 6, 2, 4
    E = H * D
    x = R.randn(B, S, E).astype(np.float32)
    qkv_w = R.randn(3, H, D, E).astype(np.float32)
    lin_w = R.randn(E, E).astype(np.float32)
    out = F.fused_multi_head_attention(
        _t(x), _t(qkv_w), _t(lin_w), dropout_rate=0.0,
        attn_dropout_rate=0.0)
    # composite reference
    qkv = x @ qkv_w.reshape(3 * H * D, E).T  # [B,S,3HD]
    qkv = qkv.reshape(B, S, 3, H, D)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    o = np.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, S, E)
    h = x + o @ lin_w
    # paddle's fused kernel ALWAYS applies the post layer norm (affine
    # params optional)
    ref = (h - h.mean(-1, keepdims=True)) / np.sqrt(
        h.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-3, atol=1e-3)


def test_masked_multihead_attention_decode_steps():
    """Two decode steps must equal full attention over the written cache."""
    B, H, D, MS = 1, 2, 4, 8
    cache = paddle.zeros([2, B, H, MS, D])
    xs = [R.randn(B, 3 * H * D).astype(np.float32) for _ in range(2)]
    outs = []
    for step, xv in enumerate(xs):
        seq = paddle.to_tensor(np.full((B,), step, np.int32))
        out, cache = F.masked_multihead_attention(
            _t(xv), cache, sequence_lengths=seq)
        outs.append(out.numpy())
    # reference: keys/values accumulated over both steps
    ks, vs = [], []
    for xv in xs:
        qkv = xv.reshape(B, 3, H, D)
        ks.append(qkv[:, 1]); vs.append(qkv[:, 2])
    q2 = xs[1].reshape(B, 3, H, D)[:, 0]
    K = np.stack(ks, 2)  # [B,H,2,D]
    V = np.stack(vs, 2)
    logits = np.einsum("bhd,bhsd->bhs", q2, K) / np.sqrt(D)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhs,bhsd->bhd", p, V).reshape(B, H * D)
    np.testing.assert_allclose(outs[1], ref, rtol=1e-4, atol=1e-4)


def test_fused_mha_qkv_weight_gets_grad():
    B, S, H, D = 1, 4, 2, 4
    E = H * D
    x = _t(R.randn(B, S, E))
    qkv_w = paddle.to_tensor(R.randn(3, H, D, E).astype(np.float32),
                             stop_gradient=False)
    lin_w = paddle.to_tensor(R.randn(E, E).astype(np.float32),
                             stop_gradient=False)
    out = F.fused_multi_head_attention(x, qkv_w, lin_w, dropout_rate=0.0,
                                       attn_dropout_rate=0.0)
    # quadratic loss: a plain sum() of the post-LN output is invariant to
    # the input up to the epsilon residue (each normalized row sums to ~0),
    # so its true gradient is numerical noise that some XLA builds round
    # to exactly 0. sum(out^2) depends on the input through LN robustly.
    (out * out).sum().backward()
    assert qkv_w.grad is not None and float(
        paddle.abs(qkv_w.grad).sum()) > 0
    assert lin_w.grad is not None


def test_fused_mha_cache_append():
    B, S, H, D = 1, 2, 2, 4
    E = H * D
    x = _t(R.randn(B, S, E))
    qkv_w = _t(R.randn(3, H, D, E))
    lin_w = _t(R.randn(E, E))
    cache = paddle.zeros([2, B, H, 0, D])
    out, new_cache = F.fused_multi_head_attention(
        x, qkv_w, lin_w, cache_kv=cache, dropout_rate=0.0,
        attn_dropout_rate=0.0)
    assert new_cache.shape == [2, B, H, S, D]


def test_masked_mha_rejects_unimplemented_args():
    cache = paddle.zeros([2, 1, 2, 4, 4])
    x = _t(R.randn(1, 3 * 2 * 4))
    with pytest.raises(NotImplementedError):
        F.masked_multihead_attention(x, cache, rotary_emb_dims=1)


def test_fused_mha_cache_receives_grad():
    B, S, H, D = 1, 2, 2, 4
    E = H * D
    x = _t(R.randn(B, S, E))
    qkv_w = _t(R.randn(3, H, D, E))
    lin_w = _t(R.randn(E, E))
    cache = paddle.to_tensor(R.randn(2, B, H, 3, D).astype(np.float32),
                             stop_gradient=False)
    out, _ = F.fused_multi_head_attention(
        x, qkv_w, lin_w, cache_kv=cache, dropout_rate=0.0,
        attn_dropout_rate=0.0)
    # quadratic loss — see test_fused_mha_qkv_weight_gets_grad
    (out * out).sum().backward()
    assert cache.grad is not None and float(
        paddle.abs(cache.grad).sum()) > 0
