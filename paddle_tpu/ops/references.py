"""Plain-XLA reference implementations for the fused Pallas kernels.

Each function here is the ``reference`` side of a ``register_oracle``
entry (see :mod:`paddle_tpu.ops.oracles`): same signature and dtype
contract as its kernel, written in straight-line jnp so a disagreement
in interpret mode localizes the bug to the kernel. All math runs in f32
and casts back to the input dtype — the same accumulation discipline the
kernels follow.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["rms_norm_reference", "layer_norm_reference",
           "bias_residual_layer_norm_reference",
           "moe_dispatch_combine_reference", "rope_reference",
           "rope_append_reference", "append_rows_reference",
           "swiglu_reference", "mla_decode_reference", "gmm_reference",
           "oproj_norm_reference", "megadecode_ffn_reference",
           "qkv_rope_append_reference"]


def rms_norm_reference(x, weight, eps: float = 1e-6):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(x.dtype)


def layer_norm_reference(x, weight, bias, eps: float = 1e-5):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def bias_residual_layer_norm_reference(x, residual, bias=None, weight=None,
                                       ln_bias=None, eps: float = 1e-5):
    H = x.shape[-1]
    b = jnp.zeros((H,), x.dtype) if bias is None else bias
    w = jnp.ones((H,), x.dtype) if weight is None else weight
    lb = jnp.zeros((H,), x.dtype) if ln_bias is None else ln_bias
    h = (x.astype(jnp.float32) + b.astype(jnp.float32)
         + residual.astype(jnp.float32))
    return layer_norm_reference(h, w, lb, eps).astype(x.dtype)


def moe_dispatch_combine_reference(keep, oh_loc, gv):
    kf = keep.astype(jnp.float32)
    of = oh_loc.astype(jnp.float32)
    gf = gv.astype(jnp.float32)
    disp = jnp.einsum("tke,tkc->tec", kf, of)
    comb = jnp.einsum("tke,tk,tkc->tec", kf, gf, of)
    return disp.astype(keep.dtype), comb.astype(keep.dtype)


def _rotate_half(x, c, s):
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


def rope_reference(q, k, cos, sin):
    c = cos.astype(jnp.float32)[None, :, None, :]
    s = sin.astype(jnp.float32)[None, :, None, :]
    qr = _rotate_half(q.astype(jnp.float32), c, s).astype(q.dtype)
    kr = _rotate_half(k.astype(jnp.float32), c, s).astype(k.dtype)
    return qr, kr


def rope_append_reference(q, k, v, cos, sin, k_pages, v_pages,
                          page_idx, page_off):
    c = cos.astype(jnp.float32)[:, None, :]           # [T, 1, D/2]
    s = sin.astype(jnp.float32)[:, None, :]
    qr = _rotate_half(q.astype(jnp.float32), c, s).astype(q.dtype)
    kr = _rotate_half(k.astype(jnp.float32), c, s)
    kp = k_pages.at[:, page_idx, page_off, :].set(
        kr.astype(k_pages.dtype).swapaxes(0, 1))
    vp = v_pages.at[:, page_idx, page_off, :].set(
        v.astype(v_pages.dtype).swapaxes(0, 1))
    return qr, kp, vp


def append_rows_reference(pages, rows, page_idx, page_off):
    return pages.at[:, page_idx, page_off, :].set(
        rows.astype(pages.dtype).swapaxes(0, 1))


def swiglu_reference(gate, up=None):
    if up is None:
        d = gate.shape[-1] // 2
        gate, up = gate[..., :d], gate[..., d:]
    gf = gate.astype(jnp.float32)
    return (gf * jax.lax.logistic(gf)
            * up.astype(jnp.float32)).astype(gate.dtype)


def _dequant_ref(w, scale, algo):
    """Whole-tensor dequant of a deploy-layout weight (fp passthrough)."""
    if algo is None:
        return w.astype(jnp.float32)
    from .quant import weight_dequantize
    return weight_dequantize(w, scale.reshape(-1).astype(jnp.float32),
                             algo)


def oproj_norm_reference(o, x, w, scale=None, bias=None, norm_weight=None,
                         norm_bias=None, *, eps: float = 1e-6,
                         norm: str = "rms", algo=None):
    """fused_oproj_norm oracle: dense dequant + f32 matmul + residual +
    rms/layer norm, returning (x_new, h)."""
    shape = x.shape
    H = shape[-1]
    x2 = x.reshape(-1, H).astype(jnp.float32)
    o2 = o.reshape(x2.shape[0], -1).astype(jnp.float32)
    p = o2 @ _dequant_ref(w, scale, algo)
    if bias is not None:
        p = p + bias.reshape(1, H).astype(jnp.float32)
    xn = x2 + p
    if norm == "rms":
        var = jnp.mean(xn * xn, axis=-1, keepdims=True)
        y = xn * jax.lax.rsqrt(var + eps)
    else:
        mu = jnp.mean(xn, axis=-1, keepdims=True)
        xc = xn - mu
        var = jnp.mean(xc * xc, axis=-1, keepdims=True)
        y = xc * jax.lax.rsqrt(var + eps)
    h = y * (jnp.ones((H,), jnp.float32) if norm_weight is None
             else norm_weight.astype(jnp.float32))
    if norm_bias is not None:
        h = h + norm_bias.astype(jnp.float32)
    return (xn.astype(x.dtype).reshape(shape),
            h.astype(x.dtype).reshape(shape))


def megadecode_ffn_reference(h, x, wg, sg=None, wu=None, su=None,
                             wd=None, sd=None, b1=None, b2=None, *,
                             act: str = "swiglu", algo=None):
    """fused_ffn oracle: gate/up dots + activation + down-proj +
    residual, all in f32."""
    shape = x.shape
    H = shape[-1]
    x2 = x.reshape(-1, H).astype(jnp.float32)
    h2 = h.reshape(-1, H).astype(jnp.float32)
    g = h2 @ _dequant_ref(wg, sg, algo)
    if b1 is not None:
        g = g + b1.reshape(1, -1).astype(jnp.float32)
    if act == "swiglu":
        u = h2 @ _dequant_ref(wu, su, algo)
        t = g * jax.lax.logistic(g) * u
    else:
        t = jax.nn.gelu(g, approximate=True)
    d = t @ _dequant_ref(wd, sd, algo)
    if b2 is not None:
        d = d + b2.reshape(1, H).astype(jnp.float32)
    return (x2 + d).astype(x.dtype).reshape(shape)


def qkv_rope_append_reference(h, w, scale, bias, cos, sin, k_pages,
                              v_pages, page_idx, page_off, *,
                              heads: int, kv_heads: int = 0,
                              head_dim: int = 0, algo=None,
                              norm_weight=None, eps: float = 1e-6,
                              nope_dim: int = 0, rope_dim: int = 0,
                              lora_rank: int = 0):
    """fused_qkv_rope_append oracle: dense dequant + f32 qkv projection
    + rotate-half rope + at[].set paged row scatter.  Standard layout
    returns (q_roped, k_pages, v_pages); MLA (lora_rank > 0) returns
    (q with its rope tail rotated, pool) with the latent rms-normed by
    ``norm_weight`` before the [latent | rope-key] row lands."""
    T = h.shape[0]
    hf = h.astype(jnp.float32)
    p = hf @ _dequant_ref(w, scale, algo)
    c = cos.astype(jnp.float32)[:, None, :]           # [T, 1, d/2]
    s = sin.astype(jnp.float32)[:, None, :]
    if lora_rank:
        dh = nope_dim + rope_dim
        nq = heads * dh
        q = p[:, :nq].reshape(T, heads, dh)
        q = jnp.concatenate(
            [q[..., :nope_dim], _rotate_half(q[..., nope_dim:], c, s)],
            axis=-1)
        lat = p[:, nq:nq + lora_rank]
        var = jnp.mean(lat * lat, axis=-1, keepdims=True)
        lat = lat * jax.lax.rsqrt(var + eps) \
            * norm_weight.reshape(1, -1).astype(jnp.float32)
        k_pe = _rotate_half(p[:, None, nq + lora_rank:], c, s)[:, 0]
        rows = jnp.concatenate([lat, k_pe], axis=-1)[:, None, :]
        pool = k_pages.at[:, page_idx, page_off, :].set(
            rows.astype(k_pages.dtype).swapaxes(0, 1))
        return q.astype(h.dtype), pool
    if bias is not None:
        p = p + bias.reshape(1, -1).astype(jnp.float32)
    D = head_dim
    q = p[:, :heads * D].reshape(T, heads, D)
    k = p[:, heads * D:(heads + kv_heads) * D].reshape(T, kv_heads, D)
    v = p[:, (heads + kv_heads) * D:].reshape(T, kv_heads, D)
    qr = _rotate_half(q, c, s).astype(h.dtype)
    kr = _rotate_half(k, c, s)
    kp = k_pages.at[:, page_idx, page_off, :].set(
        kr.astype(k_pages.dtype).swapaxes(0, 1))
    vp = v_pages.at[:, page_idx, page_off, :].set(
        v.astype(v_pages.dtype).swapaxes(0, 1))
    return qr, kp, vp


def mla_decode_reference(q_eff, q_pe, c_lat, c_pe, lengths, *,
                         scale: float, block_t: int = 1024):
    del block_t  # tiling knob; irrelevant to the math
    s = (jnp.einsum("bhr,btr->bht", q_eff.astype(jnp.float32),
                    c_lat.astype(jnp.float32))
         + jnp.einsum("bhd,btd->bht", q_pe.astype(jnp.float32),
                      c_pe.astype(jnp.float32))) * scale
    T = c_lat.shape[1]
    dead = jnp.arange(T)[None, None, :] >= \
        lengths.astype(jnp.int32)[:, None, None]
    s = jnp.where(dead, -1e30, s)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(dead, 0.0, p)
    out = jnp.einsum("bht,btr->bhr", p, c_lat.astype(jnp.float32))
    return out.astype(c_lat.dtype)


def gmm_reference(lhs, rhs, group_sizes, block_m: int = 128,
                  block_n: int = 128):
    del block_m, block_n  # tiling knobs; irrelevant to the math
    M = lhs.shape[0]
    sizes = group_sizes.astype(jnp.int32)
    ends = jnp.cumsum(sizes)
    starts = ends - sizes
    rows = jnp.arange(M, dtype=jnp.int32)[:, None]
    member = ((rows >= starts[None, :])
              & (rows < ends[None, :])).astype(jnp.float32)   # [M, G]
    per_g = jnp.einsum("mk,gkn->mgn", lhs.astype(jnp.float32),
                       rhs.astype(jnp.float32))
    out = jnp.einsum("mgn,mg->mn", per_g, member)
    return out.astype(lhs.dtype)
