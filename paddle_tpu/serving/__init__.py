"""Continuous-batching serving subsystem.

Three modules over the Pallas paged-decode kernel
(`ops/pallas_paged.py` via `ops.paged_attention`):

  - `block_allocator`: fixed pool of page_size-token KV blocks with
    refcounts, per-sequence page tables, copy-on-write prefix sharing,
    and utilization/fragmentation gauges;
  - `scheduler`: FCFS in-flight request scheduler — requests join
    mid-decode, leave instantly on EOS/max-tokens, with admission
    backpressure (`inference.Config.set_admission`) and per-request
    deadlines (`set_deadline` → falsy TimeoutResult partials);
  - `engine`: `ServingEngine.add_request/step/collect`, a fixed-shape
    jitted decode step (one compile per model/slot-count) plus chunked
    prefill, for the llama/moe, gpt and mla families.

See docs/SERVING.md ("Continuous batching") for sizing and usage.
"""

from typing import Any, Dict

from .. import observability as _obs
from ..observability import tracing as _tracing
from .block_allocator import PageBlockAllocator
from .engine import ServingEngine
from .scheduler import Request, Scheduler

__all__ = ["ServingEngine", "Request", "Scheduler", "PageBlockAllocator",
           "metrics", "slo"]


def metrics() -> Dict[str, Any]:
    """The serving.engine.* slice of the registry snapshot."""
    return {k: v for k, v in _obs.registry().snapshot().items()
            if k.startswith("serving.engine.")}


def slo(qs=(50, 90, 99)) -> Dict[str, Any]:
    """Percentile summary of the per-request SLO histograms the tracing
    layer derives at each terminal event:
    {"serving.engine.ttft_seconds": {count, mean, p50, p90, p99}, ...}
    for queue-wait / TTFT / TPOT / e2e. Histograms with no finished
    requests yet report count 0 with None quantiles."""
    return _tracing.slo_summary(qs=qs)
