"""Conv/pool op sweep vs torch-CPU references (SURVEY §7.2.5: the OCR conv
path is the non-transformer canary; torch is the independent oracle the
reference's OpTest uses NumPy for — closer semantics for convs)."""

import numpy as np
import pytest
import torch
import torch.nn.functional as TF

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

R = np.random.RandomState(9)


def _t(a):
    return paddle.to_tensor(a)


@pytest.mark.parametrize("stride,padding,dilation,groups", [
    (1, 0, 1, 1), (2, 1, 1, 1), (1, 2, 2, 1), (1, 1, 1, 2),
])
def test_conv2d_vs_torch(stride, padding, dilation, groups):
    x = R.randn(2, 4, 11, 9).astype(np.float32)
    w = R.randn(6, 4 // groups, 3, 3).astype(np.float32)
    b = R.randn(6).astype(np.float32)
    out = F.conv2d(_t(x), _t(w), _t(b), stride=stride, padding=padding,
                   dilation=dilation, groups=groups)
    ref = TF.conv2d(torch.tensor(x), torch.tensor(w), torch.tensor(b),
                    stride=stride, padding=padding, dilation=dilation,
                    groups=groups).numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-4)


def test_conv1d_and_conv3d_vs_torch():
    x1 = R.randn(2, 3, 17).astype(np.float32)
    w1 = R.randn(5, 3, 4).astype(np.float32)
    np.testing.assert_allclose(
        F.conv1d(_t(x1), _t(w1), stride=2, padding=1).numpy(),
        TF.conv1d(torch.tensor(x1), torch.tensor(w1), stride=2,
                  padding=1).numpy(), rtol=2e-4, atol=2e-4)
    x3 = R.randn(1, 2, 5, 6, 7).astype(np.float32)
    w3 = R.randn(4, 2, 3, 3, 3).astype(np.float32)
    np.testing.assert_allclose(
        F.conv3d(_t(x3), _t(w3), padding=1).numpy(),
        TF.conv3d(torch.tensor(x3), torch.tensor(w3), padding=1).numpy(),
        rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("stride,padding", [(1, 0), (2, 1)])
def test_conv2d_transpose_vs_torch(stride, padding):
    x = R.randn(2, 4, 7, 7).astype(np.float32)
    w = R.randn(4, 5, 3, 3).astype(np.float32)  # [in, out, kh, kw]
    out = F.conv2d_transpose(_t(x), _t(w), stride=stride, padding=padding)
    ref = TF.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                              stride=stride, padding=padding).numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-4)


def test_pools_vs_torch():
    x = R.randn(2, 3, 10, 8).astype(np.float32)
    np.testing.assert_allclose(
        F.max_pool2d(_t(x), 2, stride=2).numpy(),
        TF.max_pool2d(torch.tensor(x), 2, stride=2).numpy(), rtol=1e-6)
    # paddle's default exclusive=True == torch count_include_pad=False
    np.testing.assert_allclose(
        F.avg_pool2d(_t(x), 3, stride=2, padding=1).numpy(),
        TF.avg_pool2d(torch.tensor(x), 3, stride=2, padding=1,
                      count_include_pad=False).numpy(),
        rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        F.adaptive_avg_pool2d(_t(x), 1).numpy(),
        TF.adaptive_avg_pool2d(torch.tensor(x), 1).numpy(),
        rtol=1e-5, atol=1e-6)


def test_conv2d_grad_vs_torch():
    x = R.randn(1, 2, 6, 6).astype(np.float32)
    w = R.randn(3, 2, 3, 3).astype(np.float32)
    xt = paddle.to_tensor(x, stop_gradient=False)
    wt = paddle.to_tensor(w, stop_gradient=False)
    F.conv2d(xt, wt, padding=1).sum().backward()
    tx = torch.tensor(x, requires_grad=True)
    tw = torch.tensor(w, requires_grad=True)
    TF.conv2d(tx, tw, padding=1).sum().backward()
    np.testing.assert_allclose(xt.grad.numpy(), tx.grad.numpy(),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(wt.grad.numpy(), tw.grad.numpy(),
                               rtol=2e-4, atol=2e-4)


def test_batch_norm_train_eval_vs_torch():
    x = R.randn(4, 3, 5, 5).astype(np.float32)
    bn = paddle.nn.BatchNorm2D(3)
    tbn = torch.nn.BatchNorm2d(3)
    with torch.no_grad():
        tbn.weight.copy_(torch.tensor(bn.weight.numpy()))
        tbn.bias.copy_(torch.tensor(bn.bias.numpy()))
    bn.train(); tbn.train()
    y = bn(_t(x)).numpy()
    ty = tbn(torch.tensor(x)).detach().numpy()
    np.testing.assert_allclose(y, ty, rtol=1e-4, atol=1e-4)
    # running mean identical; running var differs by the bias correction:
    # paddle (and this framework) accumulate the BIASED batch variance,
    # torch the unbiased one (a documented paddle-vs-torch difference)
    np.testing.assert_allclose(bn._mean.numpy(),
                               tbn.running_mean.numpy(), rtol=1e-4,
                               atol=1e-5)
    n = x.shape[0] * x.shape[2] * x.shape[3]
    expect_var = 0.9 * 1.0 + 0.1 * (tbn.running_var.numpy() - 0.9) / 0.1 \
        * (n - 1) / n
    np.testing.assert_allclose(bn._variance.numpy(), expect_var,
                               rtol=1e-4, atol=1e-5)
    # eval mode normalizes with OUR running stats
    bn.eval()
    rm = bn._mean.numpy().reshape(1, -1, 1, 1)
    rv = bn._variance.numpy().reshape(1, -1, 1, 1)
    ref_eval = (x - rm) / np.sqrt(rv + 1e-5)
    np.testing.assert_allclose(bn(_t(x)).numpy(), ref_eval, rtol=1e-4,
                               atol=1e-4)
