"""Linear algebra (ref surface: python/paddle/tensor/linalg.py, paddle.linalg).

Decompositions lower to XLA's native QR/SVD/Cholesky/Eigh — the cuSOLVER/
LAPACK dynload layer of the reference (paddle/phi/backends/dynload/cusolver.h)
has no TPU analog to build: XLA ships these.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = [
    "t", "norm", "dist", "cross", "cholesky", "qr", "svd", "eigh",
    "eigvalsh", "inv", "pinv", "solve", "triangular_solve", "matrix_power",
    "det", "slogdet", "matrix_rank", "cond", "cov", "corrcoef", "lu",
    "cholesky_solve", "lstsq", "multi_dot", "householder_product", "pca_lowrank",
]


def t(x, name=None) -> Tensor:
    if x.ndim > 2:
        raise ValueError("paddle.t expects ndim <= 2; use transpose")
    return apply("t", lambda a: a.T, [x])


def norm(x, p=None, axis=None, keepdim=False, name=None) -> Tensor:
    """paddle.linalg.norm parity: default (p=None) is Frobenius over the
    reduced axes; p=2 over two axes is also Frobenius (paddle semantics —
    spectral norm is not what paddle's norm computes)."""
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    def impl(a):
        if ax is None or (isinstance(ax, tuple) and len(ax) == 2):
            axes = ax  # None → all
            if p in (None, "fro", 2):
                sq = jnp.sum(jnp.square(jnp.abs(a)), axis=axes, keepdims=keepdim)
                return jnp.sqrt(sq)
            if p == "nuc":
                if axes is None:
                    raise ValueError("nuclear norm requires a 2-axis tuple")
                return jnp.linalg.norm(a, ord="nuc", axis=axes, keepdims=keepdim)
            if p == np.inf:
                return jnp.max(jnp.abs(a), axis=axes, keepdims=keepdim)
            if p == -np.inf:
                return jnp.min(jnp.abs(a), axis=axes, keepdims=keepdim)
            if p == 0:
                return jnp.sum((a != 0).astype(a.dtype), axis=axes,
                               keepdims=keepdim)
            if p == 1:
                return jnp.sum(jnp.abs(a), axis=axes, keepdims=keepdim)
            return jnp.sum(jnp.abs(a) ** p, axis=axes,
                           keepdims=keepdim) ** (1.0 / p)
        axi = ax[0] if isinstance(ax, tuple) else ax
        q = 2 if p in (None, "fro") else p
        if q == np.inf:
            return jnp.max(jnp.abs(a), axis=axi, keepdims=keepdim)
        if q == -np.inf:
            return jnp.min(jnp.abs(a), axis=axi, keepdims=keepdim)
        if q == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axi, keepdims=keepdim)
        if q == 2:
            return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(a)), axis=axi,
                                    keepdims=keepdim))
        return jnp.sum(jnp.abs(a) ** q, axis=axi, keepdims=keepdim) ** (1.0 / q)
    return apply("norm", impl, [x])


def dist(x, y, p=2, name=None) -> Tensor:
    def impl(a, b):
        d = jnp.abs(a - b).reshape(-1)
        if p == 0:
            return jnp.sum((d != 0).astype(a.dtype))
        if p == np.inf:
            return jnp.max(d)
        if p == -np.inf:
            return jnp.min(d)
        return jnp.sum(d ** p) ** (1.0 / p)
    return apply("dist", impl, [x, y])


def cross(x, y, axis=9, name=None) -> Tensor:
    ax = axis
    if ax == 9:  # paddle default: first axis of size 3
        ax = next(i for i, s in enumerate(x.shape) if s == 3)
    return apply("cross", lambda a, b: jnp.cross(a, b, axis=ax), [x, y])


def cholesky(x, upper=False, name=None) -> Tensor:
    def impl(a):
        low = jnp.linalg.cholesky(a)
        return jnp.swapaxes(low, -1, -2) if upper else low
    return apply("cholesky", impl, [x])


def qr(x, mode="reduced", name=None):
    out = apply("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), [x]) \
        if mode != "r" else None
    if mode == "r":
        return apply("qr_r", lambda a: jnp.linalg.qr(a, mode="r"), [x])
    return out


def svd(x, full_matrices=False, name=None):
    return apply("svd",
                 lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)),
                 [x])


def eigh(x, UPLO="L", name=None):
    return apply("eigh", lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), [x])


def eigvalsh(x, UPLO="L", name=None) -> Tensor:
    return apply("eigvalsh", lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), [x])


def inv(x, name=None) -> Tensor:
    return apply("inv", jnp.linalg.inv, [x])


def pinv(x, rcond=1e-15, hermitian=False, name=None) -> Tensor:
    return apply("pinv",
                 lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian),
                 [x])


def solve(x, y, name=None) -> Tensor:
    return apply("solve", jnp.linalg.solve, [x, y])


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None) -> Tensor:
    def impl(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply("triangular_solve", impl, [x, y])


def cholesky_solve(x, y, upper=False, name=None) -> Tensor:
    def impl(b, l):
        z = jax.scipy.linalg.solve_triangular(l, b, lower=not upper)
        return jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(l, -1, -2), z, lower=upper)
    return apply("cholesky_solve", impl, [x, y])


def matrix_power(x, n, name=None) -> Tensor:
    return apply("matrix_power", lambda a: jnp.linalg.matrix_power(a, n), [x])


def det(x, name=None) -> Tensor:
    return apply("det", jnp.linalg.det, [x])


def slogdet(x, name=None):
    def impl(a):
        s, l = jnp.linalg.slogdet(a)
        return jnp.stack([s, l]) if s.ndim == 0 else jnp.stack([s, l])
    return apply("slogdet", impl, [x])


def matrix_rank(x, tol=None, hermitian=False, name=None) -> Tensor:
    return Tensor(jnp.linalg.matrix_rank(x._data, rtol=tol))


def cond(x, p=None, name=None) -> Tensor:
    return apply("cond", lambda a: jnp.linalg.cond(a, p=p), [x])


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None) -> Tensor:
    fw = fweights._data if isinstance(fweights, Tensor) else fweights
    aw = aweights._data if isinstance(aweights, Tensor) else aweights
    return apply("cov",
                 lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0,
                                   fweights=fw, aweights=aw), [x])


def corrcoef(x, rowvar=True, name=None) -> Tensor:
    return apply("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), [x])


def lu(x, pivot=True, get_infos=False, name=None):
    lu_, piv = apply("lu", lambda a: tuple(jax.scipy.linalg.lu_factor(a)), [x])
    if get_infos:
        info = Tensor(jnp.zeros((), jnp.int32))
        return lu_, piv, info
    return lu_, piv


def lstsq(x, y, rcond=None, driver=None, name=None):
    def impl(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return apply("lstsq", impl, [x, y])


def multi_dot(tensors, name=None) -> Tensor:
    return apply("multi_dot", lambda *arrs: jnp.linalg.multi_dot(arrs),
                 list(tensors))


def householder_product(x, tau, name=None) -> Tensor:
    def impl2d(a, t_):
        m, n = a.shape
        q = jnp.eye(m, dtype=a.dtype)
        for i in range(n):
            v = jnp.concatenate([jnp.zeros(i, a.dtype), jnp.ones(1, a.dtype),
                                 a[i + 1:, i]])
            h = jnp.eye(m, dtype=a.dtype) - t_[i] * jnp.outer(v, v)
            q = q @ h
        return q[:, :n]

    def impl(a, t_):
        if a.ndim == 2:
            return impl2d(a, t_)
        batch = a.shape[:-2]
        af = a.reshape((-1,) + a.shape[-2:])
        tf = t_.reshape((-1, t_.shape[-1]))
        out = jax.vmap(impl2d)(af, tf)
        return out.reshape(batch + out.shape[-2:])
    return apply("householder_product", impl, [x, tau])


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    def impl(a):
        b = a - jnp.mean(a, axis=-2, keepdims=True) if center else a
        u, s, vt = jnp.linalg.svd(b, full_matrices=False)
        k = q if q is not None else min(6, *b.shape[-2:])
        return u[..., :k], s[..., :k], jnp.swapaxes(vt, -1, -2)[..., :k]
    return apply("pca_lowrank", impl, [x])


# ---------------------------------------------------------------------------
# long-tail linalg surface
# ---------------------------------------------------------------------------
def mm(x, y, name=None) -> Tensor:
    return apply("mm", jnp.matmul, [x, y])


def bmm(x, y, name=None) -> Tensor:
    if x.ndim != 3 or y.ndim != 3:
        raise ValueError("bmm expects 3-D inputs")
    return apply("bmm", jnp.matmul, [x, y])


def mv(x, vec, name=None) -> Tensor:
    return apply("mv", jnp.matmul, [x, vec])


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None) -> Tensor:
    return apply("addmm", lambda i, a, b: beta * i + alpha * (a @ b),
                 [input, x, y])


inverse = inv


def tensordot(x, y, axes=2, name=None) -> Tensor:
    ax = axes
    if isinstance(ax, (list, tuple)):
        ax = tuple(tuple(a) if isinstance(a, (list, tuple)) else a for a in ax)
    return apply("tensordot", lambda a, b: jnp.tensordot(a, b, axes=ax),
                 [x, y])


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None) -> Tensor:
    """Pairwise p-distance between row sets: [..., M, D] × [..., N, D] →
    [..., M, N]."""
    def impl(a, b):
        diff = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            sq = jnp.sum(jnp.square(diff), -1)
            # masked subgradient at coincident rows: d/dx sqrt(0) is inf and
            # inf*0 = NaN would poison the whole gradient
            zero = sq == 0
            return jnp.where(zero, 0.0, jnp.sqrt(jnp.where(zero, 1.0, sq)))
        if p == float("inf"):
            return jnp.max(jnp.abs(diff), -1)
        return jnp.sum(jnp.abs(diff) ** p, -1) ** (1.0 / p)
    return apply("cdist", impl, [x, y])


def pdist(x, p=2.0, name=None) -> Tensor:
    """Condensed pairwise distance of rows ([N, D] → [N*(N-1)/2])."""
    n = x.shape[0]
    iu = np.triu_indices(n, k=1)
    def impl(a):
        d = a[:, None, :] - a[None, :, :]
        if p == 2.0:
            sq = jnp.sum(jnp.square(d), -1)
            zero = sq == 0
            full = jnp.where(zero, 0.0, jnp.sqrt(jnp.where(zero, 1.0, sq)))
        elif p == float("inf"):
            full = jnp.max(jnp.abs(d), -1)
        else:
            full = jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)
        return full[iu]
    return apply("pdist", impl, [x])


__all__ += ["mm", "bmm", "mv", "addmm", "inverse", "tensordot", "cdist",
            "pdist"]


# ---------------------------------------------------------------------------
# linalg long tail (ref: python/paddle/tensor/linalg.py — VERDICT r1 item 8)
# ---------------------------------------------------------------------------
def matrix_transpose(x, name=None) -> Tensor:
    return apply("matrix_transpose", lambda a: jnp.swapaxes(a, -2, -1), [x])


def vecdot(x, y, axis=-1, name=None) -> Tensor:
    return apply("vecdot",
                 lambda a, b: jnp.sum(a * b, axis=axis), [x, y])


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None) -> Tensor:
    def impl(a):
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axis,
                           keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=axis,
                       keepdims=keepdim) ** (1.0 / p)
    return apply("vector_norm", impl, [x])


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None) -> Tensor:
    def impl(a):
        r, c = axis
        if p == "fro":
            return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(a)), axis=axis,
                                    keepdims=keepdim))
        kept = sorted(ax % a.ndim for ax in (r, c))
        if p == "nuc":
            s = jnp.linalg.svd(jnp.moveaxis(a, (r, c), (-2, -1)),
                               compute_uv=False)
            out = jnp.sum(s, -1)
            return jnp.expand_dims(out, kept) if keepdim else out
        if p in (1, -1):  # max/min column abs-sum
            col = jnp.sum(jnp.abs(a), axis=r, keepdims=True)
            red = jnp.max if p == 1 else jnp.min
            out = red(col, axis=c, keepdims=True)
            return out if keepdim else jnp.squeeze(out, axis)
        if p in (2, -2):
            s = jnp.linalg.svd(jnp.moveaxis(a, (r, c), (-2, -1)),
                               compute_uv=False)
            out = s[..., 0] if p == 2 else s[..., -1]
            return jnp.expand_dims(out, kept) if keepdim else out
        if p in (float("inf"), float("-inf")):  # max/min row abs-sum
            row = jnp.sum(jnp.abs(a), axis=c, keepdims=True)
            red = jnp.max if p == float("inf") else jnp.min
            out = red(row, axis=r, keepdims=True)
            return out if keepdim else jnp.squeeze(out, axis)
        raise ValueError(f"unsupported matrix norm order {p!r}")
    return apply("matrix_norm", impl, [x])


def svdvals(x, name=None) -> Tensor:
    return apply("svdvals",
                 lambda a: jnp.linalg.svd(a, compute_uv=False), [x])


def matrix_exp(x, name=None) -> Tensor:
    import jax.scipy.linalg as jsl
    return apply("matrix_exp", jsl.expm, [x])


def cholesky_inverse(x, upper=False, name=None) -> Tensor:
    """inv(A) from its Cholesky factor (ref: paddle.linalg.cholesky_inverse)."""
    def impl(L):
        n = L.shape[-1]
        eye = jnp.eye(n, dtype=L.dtype)
        import jax.scipy.linalg as jsl
        li = jsl.solve_triangular(L, eye, lower=not upper)
        return li.T @ li if not upper else li @ li.T
    return apply("cholesky_inverse", impl, [x])


def eig(x, name=None):
    """General (non-symmetric) eigendecomposition. Eager-only: XLA has
    no device kernel for the unsymmetric QR algorithm (the reference
    runs it on CPU too — paddle's eig kernel is host LAPACK)."""
    a = np.asarray(x._data if isinstance(x, Tensor) else x)
    w, v = np.linalg.eig(a)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigvals(x, name=None) -> Tensor:
    a = np.asarray(x._data if isinstance(x, Tensor) else x)
    return Tensor(jnp.asarray(np.linalg.eigvals(a)))


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    """Unpack paddle.linalg.lu output into (P, L, U)."""
    lu = lu_data._data if isinstance(lu_data, Tensor) else jnp.asarray(lu_data)
    piv = np.asarray(lu_pivots._data if isinstance(lu_pivots, Tensor)
                     else lu_pivots).astype(np.int64)
    m, n = lu.shape[-2], lu.shape[-1]
    k = min(m, n)
    L = jnp.tril(lu[..., :, :k], -1) + jnp.eye(m, k, dtype=lu.dtype)
    U = jnp.triu(lu[..., :k, :])
    # pivots are THIS framework's lu convention (0-based sequential row
    # swaps, scipy lu_factor style — paddle's kernel is 1-based)
    if piv.ndim > 1:
        raise NotImplementedError("batched lu_unpack pivots")
    perm = np.arange(m)
    for i in range(piv.shape[-1]):
        j = int(piv[i])
        perm[[i, j]] = perm[[j, i]]
    P = jnp.eye(m, dtype=lu.dtype)[:, perm]
    out = []
    out.append(Tensor(P) if unpack_pivots else None)
    out.append(Tensor(L) if unpack_ludata else None)
    out.append(Tensor(U) if unpack_ludata else None)
    return tuple(out)


def ormqr(x, tau, other, left=True, transpose=False, name=None) -> Tensor:
    """Multiply `other` by the IMPLICIT m x m Q of a householder QR
    (ref: paddle.linalg.ormqr). Reflections are applied directly —
    householder_product's thin Q would be wrong (and shape-invalid) for
    non-square x."""
    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    if xa.ndim != 2:
        raise NotImplementedError(
            "ormqr supports 2-D factors (batched reflections pending, "
            "like lu_unpack's batched pivots)")

    def impl(a, t, o):
        m, k = a.shape[-2], a.shape[-1]
        rows = jnp.arange(m)

        def refl(i, vec):
            v = jnp.where(rows < i, 0.0,
                          jnp.where(rows == i, 1.0, a[:, i]))
            return vec - t[i] * v * jnp.vdot(v, vec)

        def apply_q(vec, trans):
            # Q = H1...Hk; Qx applies Hk first, Q^T x applies H1 first
            order = range(k) if trans else range(k - 1, -1, -1)
            for i in order:
                vec = refl(i, vec)
            return vec

        if left:
            return jax.vmap(lambda col: apply_q(col, transpose),
                            in_axes=1, out_axes=1)(o)
        # o @ Q == (Q^T o^T)^T; o @ Q^T == (Q o^T)^T
        ot = jnp.swapaxes(o, -2, -1)
        res = jax.vmap(lambda col: apply_q(col, not transpose),
                       in_axes=1, out_axes=1)(ot)
        return jnp.swapaxes(res, -2, -1)
    return apply("ormqr", impl, [x, tau, other])


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (ref: paddle.linalg.svd_lowrank).
    Differentiable (qr/svd/matmul chain through the dispatch tape); the
    gaussian sketch is drawn once outside the traced impl."""
    from ..framework.random import next_key
    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    n = xa.shape[-1]
    g = jax.random.normal(next_key(), xa.shape[:-2] + (n, q), xa.dtype)
    Ma = None if M is None else (M._data if isinstance(M, Tensor)
                                 else jnp.asarray(M))

    def impl(a):
        am = a if Ma is None else a - Ma
        y = am @ g
        for _ in range(niter):
            y = am @ (jnp.swapaxes(am, -2, -1) @ y)
        qb, _ = jnp.linalg.qr(y)
        b = jnp.swapaxes(qb, -2, -1) @ am
        u, s, vh = jnp.linalg.svd(b, full_matrices=False)
        return qb @ u, s, jnp.swapaxes(vh, -2, -1)
    return apply("svd_lowrank", impl, [x])


__all__ += ["matrix_transpose", "vecdot", "vector_norm", "matrix_norm",
            "svdvals", "matrix_exp", "cholesky_inverse", "eig", "eigvals",
            "lu_unpack", "ormqr", "svd_lowrank"]
