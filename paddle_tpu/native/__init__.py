"""ctypes bindings for the native C++ runtime components (csrc/native.cc).

Native-code contract (SURVEY §2.1 "TPU-native equivalents ... in C++ where
the reference is native"): flags registry, TCPStore coordination service,
host profiler. The shared library is compiled once on first import (g++,
cached next to the source); every binding has a pure-Python fallback so the
framework stays importable on machines without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time
from typing import Optional

__all__ = ["lib", "available", "TCPStore", "RecordEvent", "prof_enable",
           "prof_export", "native_flag_define", "native_flag_get",
           "native_flag_set"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "..", "csrc", "native.cc")
_SO = os.path.join(_DIR, "_native.so")

lib = None


def _build() -> Optional[str]:
    src = os.path.abspath(_SRC)
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(src):
        return _SO
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
             src, "-o", _SO],
            check=True, capture_output=True, timeout=180)
        return _SO
    except Exception:
        return None


def _load():
    global lib
    so = _build()
    if so is None:
        return
    try:
        L = ctypes.CDLL(so)
    except OSError:
        return
    L.pt_flag_define.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    L.pt_flag_set.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    L.pt_flag_get.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    L.pt_flag_get.restype = ctypes.c_int
    L.pt_store_server_start.argtypes = [ctypes.c_int,
                                        ctypes.POINTER(ctypes.c_int)]
    L.pt_store_server_start.restype = ctypes.c_longlong
    L.pt_store_server_stop.argtypes = [ctypes.c_longlong]
    L.pt_store_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                   ctypes.c_int]
    L.pt_store_connect.restype = ctypes.c_int
    L.pt_store_close.argtypes = [ctypes.c_int]
    L.pt_store_set.argtypes = [ctypes.c_int, ctypes.c_char_p,
                               ctypes.c_char_p, ctypes.c_int]
    L.pt_store_set.restype = ctypes.c_int
    L.pt_store_get.argtypes = [ctypes.c_int, ctypes.c_char_p,
                               ctypes.c_char_p, ctypes.c_int]
    L.pt_store_get.restype = ctypes.c_int
    L.pt_store_add.argtypes = [ctypes.c_int, ctypes.c_char_p,
                               ctypes.c_longlong]
    L.pt_store_add.restype = ctypes.c_longlong
    L.pt_store_wait.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
                                ctypes.c_char_p, ctypes.c_int]
    L.pt_store_wait.restype = ctypes.c_int
    L.pt_store_delete.argtypes = [ctypes.c_int, ctypes.c_char_p]
    L.pt_prof_enable.argtypes = [ctypes.c_int]
    L.pt_prof_enabled.restype = ctypes.c_int
    L.pt_prof_begin.restype = ctypes.c_ulonglong
    L.pt_prof_end.argtypes = [ctypes.c_char_p, ctypes.c_ulonglong]
    L.pt_prof_export.argtypes = [ctypes.c_char_p, ctypes.c_int]
    L.pt_prof_export.restype = ctypes.c_int
    L.pt_prof_event_count.restype = ctypes.c_int
    L.pt_bpe_create.restype = ctypes.c_longlong
    L.pt_bpe_add_token.argtypes = [ctypes.c_longlong, ctypes.c_char_p,
                                   ctypes.c_int]
    L.pt_bpe_add_merge.argtypes = [ctypes.c_longlong, ctypes.c_char_p,
                                   ctypes.c_char_p, ctypes.c_int]
    L.pt_bpe_set_unk.argtypes = [ctypes.c_longlong, ctypes.c_int]
    L.pt_bpe_free.argtypes = [ctypes.c_longlong]
    L.pt_bpe_encode_piece.argtypes = [ctypes.c_longlong, ctypes.c_char_p,
                                      ctypes.POINTER(ctypes.c_int),
                                      ctypes.c_int]
    L.pt_bpe_encode_piece.restype = ctypes.c_int
    lib = L


_load()


def available() -> bool:
    return lib is not None


# ---------------------------------------------------------------------------
# flags (native registry; paddle_tpu.flags remains the python-facing API)
# ---------------------------------------------------------------------------

def native_flag_define(name: str, default: str) -> None:
    if lib is not None:
        lib.pt_flag_define(name.encode(), str(default).encode())


def native_flag_set(name: str, value: str) -> None:
    if lib is not None:
        lib.pt_flag_set(name.encode(), str(value).encode())


def native_flag_get(name: str) -> Optional[str]:
    if lib is None:
        return None
    buf = ctypes.create_string_buffer(4096)
    n = lib.pt_flag_get(name.encode(), buf, 4096)
    if n < 0:
        return None
    return buf.value.decode()


# ---------------------------------------------------------------------------
# TCPStore (ref API: paddle.distributed.TCPStore-like kv/barrier)
# ---------------------------------------------------------------------------

class _PyStoreServer:
    """Pure-Python fallback store server (same wire-free semantics,
    in-process only)."""

    def __init__(self):
        self.kv = {}
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)


class TCPStore:
    """kv + barrier rendezvous (ref: paddle/phi/core/distributed/store/
    tcp_store.cc). is_master starts the C++ server thread; every instance is
    also a client."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, world_size: int = 1,
                 timeout: float = 60.0):
        self.world_size = world_size
        self.timeout = timeout
        self._server = None
        self._py = None
        self._lock = threading.Lock()  # serialize requests on this conn
        if lib is None:
            # in-process fallback: master-only, no cross-process support
            self._py = _PyStoreServer()
            self.host, self.port = host, port
            return
        if is_master:
            actual = ctypes.c_int(0)
            self._server = lib.pt_store_server_start(port,
                                                     ctypes.byref(actual))
            if self._server < 0:
                raise RuntimeError(f"TCPStore bind failed on port {port}")
            port = actual.value
        self.host, self.port = host, port
        self._fd = lib.pt_store_connect(host.encode(), port,
                                        int(timeout * 1000))
        if self._fd < 0:
            raise TimeoutError(f"TCPStore connect to {host}:{port} failed")

    # -- kv ------------------------------------------------------------------
    def set(self, key: str, value) -> None:
        data = value if isinstance(value, bytes) else str(value).encode()
        if self._py is not None:
            with self._py.cond:
                self._py.kv[key] = data
                self._py.cond.notify_all()
            return
        with self._lock:
            r = lib.pt_store_set(self._fd, key.encode(), data, len(data))
        if r < 0:
            raise RuntimeError("TCPStore set failed")

    def get(self, key: str) -> Optional[bytes]:
        if self._py is not None:
            with self._py.lock:
                return self._py.kv.get(key)
        buf = ctypes.create_string_buffer(1 << 20)
        with self._lock:
            n = lib.pt_store_get(self._fd, key.encode(), buf, 1 << 20)
        if n < 0:
            return None
        return buf.raw[:n]

    def add(self, key: str, delta: int = 1) -> int:
        if self._py is not None:
            with self._py.cond:
                cur = int(self._py.kv.get(key, b"0")) + delta
                self._py.kv[key] = str(cur).encode()
                self._py.cond.notify_all()
                return cur
        with self._lock:
            r = lib.pt_store_add(self._fd, key.encode(), delta)
        if r < 0:
            raise RuntimeError("TCPStore add failed")
        return int(r)

    def wait(self, key: str, timeout: Optional[float] = None) -> bytes:
        tmo = self.timeout if timeout is None else timeout
        if self._py is not None:
            with self._py.cond:
                end = time.monotonic() + tmo
                while key not in self._py.kv:
                    left = end - time.monotonic()
                    if left <= 0:
                        raise TimeoutError(f"wait({key}) timed out")
                    self._py.cond.wait(left)
                return self._py.kv[key]
        buf = ctypes.create_string_buffer(1 << 20)
        with self._lock:
            n = lib.pt_store_wait(self._fd, key.encode(), int(tmo * 1000),
                                  buf, 1 << 20)
        if n < 0:
            raise TimeoutError(f"wait({key}) timed out")
        return buf.raw[:n]

    def delete(self, key: str) -> None:
        if self._py is not None:
            with self._py.lock:
                self._py.kv.pop(key, None)
            return
        with self._lock:
            lib.pt_store_delete(self._fd, key.encode())

    # -- barrier -------------------------------------------------------------
    def barrier(self, name: str = "default",
                timeout: Optional[float] = None) -> None:
        n = self.add(f"__barrier/{name}/count", 1)
        if n == self.world_size:
            self.set(f"__barrier/{name}/done", b"1")
        self.wait(f"__barrier/{name}/done", timeout)

    def close(self) -> None:
        if self._py is not None:
            return
        if getattr(self, "_fd", -1) >= 0:
            lib.pt_store_close(self._fd)
            self._fd = -1
        if self._server:
            lib.pt_store_server_stop(self._server)
            self._server = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# profiler (RecordEvent + chrome trace export)
# ---------------------------------------------------------------------------

_py_events = []
_py_enabled = False
_py_lock = threading.Lock()


def prof_enable(on: bool = True) -> None:
    global _py_enabled
    if lib is not None:
        lib.pt_prof_enable(1 if on else 0)
    _py_enabled = bool(on)


class RecordEvent:
    """ref: paddle.profiler.RecordEvent / C++ RecordEvent instrumentation.
    Usable as context manager or decorator; ~no cost when profiling is off."""

    def __init__(self, name: str):
        self.name = name
        self._begin = 0

    def __enter__(self):
        if lib is not None:
            self._begin = lib.pt_prof_begin()
        elif _py_enabled:
            self._begin = time.perf_counter_ns() // 1000
        return self

    def __exit__(self, *exc):
        if lib is not None:
            lib.pt_prof_end(self.name.encode(), self._begin)
        elif _py_enabled and self._begin:
            end = time.perf_counter_ns() // 1000
            with _py_lock:
                _py_events.append((self.name, self._begin,
                                   end - self._begin))
        return False

    def __call__(self, fn):
        def wrapped(*a, **kw):
            with RecordEvent(self.name):
                return fn(*a, **kw)
        return wrapped


def prof_export(path: str, pid: int = 0) -> int:
    """Write chrome://tracing JSON; returns event count."""
    if lib is not None:
        return int(lib.pt_prof_export(path.encode(), pid))
    import json
    with _py_lock:
        evs = [{"name": n, "ph": "X", "pid": pid, "tid": 0, "ts": ts,
                "dur": dur, "cat": "host"} for n, ts, dur in _py_events]
    with open(path, "w") as f:
        json.dump({"traceEvents": evs}, f)
    return len(evs)


def prof_clear() -> None:
    if lib is not None:
        lib.pt_prof_clear()
    with _py_lock:
        _py_events.clear()


def prof_event_count() -> int:
    if lib is not None:
        return int(lib.pt_prof_event_count())
    with _py_lock:
        return len(_py_events)


# ---------------------------------------------------------------------------
# Fast BPE (ref: PaddleNLP fast_tokenizer C++ — the merge-loop hot path)
# ---------------------------------------------------------------------------
class NativeBPE:
    """C++ byte-pair merge loop (no caching here — BPETokenizer.encode
    memoizes per piece on the python side). Construct from the same
    (vocab, merges) a text.BPETokenizer holds; encode_piece operates on
    pre-tokenized, byte-alphabet-mapped pieces."""

    def __init__(self, vocab, merges, unk_id: int = 0):
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._h = lib.pt_bpe_create()
        for tok, i in vocab.items():
            lib.pt_bpe_add_token(self._h, tok.encode("utf-8"), int(i))
        for rank, (l, r) in enumerate(merges):
            lib.pt_bpe_add_merge(self._h, l.encode("utf-8"),
                                 r.encode("utf-8"), rank)
        lib.pt_bpe_set_unk(self._h, int(unk_id))

    def encode_piece(self, piece: str):
        # per-call buffer: ctypes releases the GIL during the C call, so a
        # shared buffer would race under threaded data loading. The C side
        # returns the FULL count; retry with a bigger buffer if truncated.
        cap = 4096
        raw = piece.encode("utf-8")
        while True:
            buf = (ctypes.c_int * cap)()
            n = lib.pt_bpe_encode_piece(self._h, raw, buf, cap)
            if n < 0:
                raise RuntimeError("invalid native BPE handle")
            if n <= cap:
                return list(buf[:n])
            cap = n

    def close(self):
        if getattr(self, "_h", None) and lib is not None:
            lib.pt_bpe_free(self._h)
            self._h = 0

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
