"""Tensor attribute helpers (ref: python/paddle/tensor/attribute.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["rank", "shape", "real", "imag", "is_complex", "is_integer",
           "is_floating_point"]


def rank(x) -> Tensor:
    return Tensor(jnp.asarray(x.ndim, jnp.int32))


def shape(x) -> Tensor:
    return Tensor(jnp.asarray(x.shape, jnp.int32))


def real(x, name=None) -> Tensor:
    return apply("real", jnp.real, [x])


def imag(x, name=None) -> Tensor:
    return apply("imag", jnp.imag, [x])


def is_complex(x) -> bool:
    return np.issubdtype(x.dtype, np.complexfloating)


def is_integer(x) -> bool:
    return np.issubdtype(x.dtype, np.integer)


def is_floating_point(x) -> bool:
    return np.issubdtype(x.dtype, np.floating) or x.dtype == jnp.bfloat16


def is_empty(x, name=None):
    n = 1
    for s in x.shape:
        n *= s
    return Tensor(jnp.asarray(n == 0))


def tolist(x):
    return x.numpy().tolist()


def as_complex(x, name=None):
    """[..., 2] real pairs → complex (ref: as_complex op)."""
    return apply("as_complex",
                 lambda a: jax.lax.complex(a[..., 0], a[..., 1]), [x])


def as_real(x, name=None):
    return apply("as_real",
                 lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], -1), [x])


def polar(abs, angle, name=None):
    return apply("polar",
                 lambda r, t: jax.lax.complex(r * jnp.cos(t), r * jnp.sin(t)),
                 [abs, angle])


__all__ += ["is_empty", "tolist", "as_complex", "as_real", "polar"]
