"""CINN-parity fusion pass (ref: paddle/cinn — ApplyCinnPass marks fusible
subgraphs, compiles them, and replaces them with JIT-kernel ops; SURVEY §2.1
'CINN fusion compiler' row and §7.1 L7).

TPU-native substitution: XLA already performs the elementwise/reduction
fusion CINN provides. The beyond-XLA deliverable is PATTERN fusion — regions
XLA will not fuse into one kernel on its own. This pass operates on the
traced jaxpr (the IR of this framework) and rewrites recognized
scaled-dot-product-attention chains

    dot_general(q, k^T) [* scale] -> softmax(axis=-1) -> dot_general(., v)

into the Pallas TPU flash-attention kernel, exactly as CINN replaces a fused
group with a compiled kernel op. Gated by FLAGS_use_fusion_compiler
(parity: FLAGS_use_cinn); `fuse(fn)` is also a standalone transform.

Matching is conservative: only single-consumer chains with the canonical
[B, H, S, D] dot dimension numbers are rewritten; anything else is left to
XLA untouched. The matched interior ops are skipped entirely (their values
are never materialized) unless some other consumer needs them.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Set

import jax
import jax.numpy as jnp
from jax.extend import core as jcore

__all__ = ["fuse", "match_sdpa_patterns", "match_rmsnorm_patterns",
           "match_swiglu_patterns", "match_bias_residual_ln_patterns",
           "match_moe_dispatch_patterns", "PATTERNS"]


def _only_consumer(uses: Dict[Any, List[int]], var, eqn_idx: int) -> bool:
    return uses.get(var, []) == [eqn_idx]


def _build_use_map(jaxpr) -> Dict[Any, List[int]]:
    uses: Dict[Any, List[int]] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not isinstance(v, jcore.Literal):
                uses.setdefault(v, []).append(i)
    for v in jaxpr.outvars:
        if not isinstance(v, jcore.Literal):
            uses.setdefault(v, []).append(-1)  # jaxpr output = external use
    return uses


def match_sdpa_patterns(jaxpr) -> List[dict]:
    """Find non-causal, unmasked SDPA chains. Returns matches with the
    q/k/v vars, the scale, the producing eqn index of the final dot, and
    the set of interior eqn indices skippable when fused."""
    eqns = jaxpr.eqns
    producer: Dict[Any, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.outvars:
            producer[v] = i
    uses = _build_use_map(jaxpr)

    def prod(v):
        return eqns[producer[v]] if v in producer else None

    matches = []
    for i, eqn in enumerate(eqns):
        if eqn.primitive.name != "dot_general":
            continue
        # final dot: [B,H,Sq,Sk] @ [B,H,Sk,D] contracting 3 with 2
        dn = eqn.params.get("dimension_numbers")
        if dn != (((3,), (2,)), ((0, 1), (0, 1))):
            continue
        probs_var, v_var = eqn.invars
        if isinstance(probs_var, jcore.Literal):
            continue
        chain: Set[int] = set()

        def follow(var):
            """Skip convert_element_type links (bf16 softmax inserts f32
            accumulation converts), recording them in the chain."""
            while True:
                e = prod(var)
                if e is None or e.primitive.name != "convert_element_type":
                    return var
                chain.add(producer[var])
                var = e.invars[0]

        def step(var, prim_name):
            """var's producer if it is `prim_name` (through converts);
            records the eqn into `chain`."""
            var = follow(var)
            e = prod(var)
            if e is None or e.primitive.name != prim_name:
                return None
            chain.add(producer[var])
            return e

        e_div = step(probs_var, "div")
        if e_div is None:
            continue
        exp_var, denom_var = e_div.invars
        e_bcast_sum = step(denom_var, "broadcast_in_dim")
        if e_bcast_sum is None:
            continue
        e_sum = step(e_bcast_sum.invars[0], "reduce_sum")
        if e_sum is None or follow(e_sum.invars[0]) is not follow(exp_var):
            continue
        exp_var = follow(exp_var)
        chain.add(producer[exp_var])
        e_exp = prod(exp_var)
        if e_exp is None or e_exp.primitive.name != "exp":
            continue
        e_sub = step(e_exp.invars[0], "sub")
        if e_sub is None:
            continue
        logits_var, max_b_var = e_sub.invars
        # max side: [stop_gradient] <- broadcast <- [max(-inf)] <- reduce_max
        mv = max_b_var
        e_sg = prod(mv)
        if e_sg is not None and e_sg.primitive.name == "stop_gradient":
            chain.add(producer[mv])
            mv = e_sg.invars[0]
        e_bc = step(mv, "broadcast_in_dim")
        if e_bc is None:
            continue
        mv = e_bc.invars[0]
        e_max = prod(mv)
        if e_max is not None and e_max.primitive.name == "max":
            chain.add(producer[mv])
            ins = [x for x in e_max.invars if not isinstance(x, jcore.Literal)]
            if len(ins) != 1:
                continue
            mv = ins[0]
        e_rmax = step(mv, "reduce_max")
        if e_rmax is None or e_rmax.invars[0] is not logits_var:
            continue
        # logits: dot_general [* scale]
        scale = None
        lv = logits_var
        e_mul = prod(lv)
        if e_mul is not None and e_mul.primitive.name == "mul":
            lits = [x for x in e_mul.invars if isinstance(x, jcore.Literal)]
            var_ins = [x for x in e_mul.invars
                       if not isinstance(x, jcore.Literal)]
            if len(lits) == 1 and len(var_ins) == 1:
                scale = float(lits[0].val)
                chain.add(producer[lv])
                lv = var_ins[0]
        e_dot1 = prod(lv)
        if e_dot1 is None or e_dot1.primitive.name != "dot_general":
            continue
        dn1 = e_dot1.params.get("dimension_numbers")
        if dn1 != (((3,), (3,)), ((0, 1), (0, 1))):
            continue
        chain.add(producer[lv])
        q_var, k_var = e_dot1.invars
        D = q_var.aval.shape[-1]

        # interior eqn outputs used OUTSIDE the chain force those eqns to
        # stay — and transitively their upstream chain producers, since a
        # kept eqn still reads its inputs
        keep: Set[int] = set()
        for idx in chain:
            for ov in eqns[idx].outvars:
                ext = [u for u in uses.get(ov, []) if u != i and u not in chain]
                if ext:
                    keep.add(idx)
        changed = True
        while changed:
            changed = False
            for idx in list(keep):
                for iv in eqns[idx].invars:
                    if isinstance(iv, jcore.Literal):
                        continue
                    p = producer.get(iv)
                    if p is not None and p in chain and p not in keep:
                        keep.add(p)
                        changed = True
        if not (chain - keep):
            # every interior value is consumed elsewhere (typical when the
            # backward pass was traced into the same jaxpr and reads the
            # probs): fusing would ADD a kernel on top of the fully
            # materialized chain — a pessimization, so skip. To fuse
            # training, apply `fuse` to the forward fn and differentiate
            # the result (AD then uses the kernel's custom VJP).
            continue
        matches.append({
            "pattern": "sdpa", "final": i, "chain": chain - keep,
            "q": q_var, "k": k_var, "v": v_var,
            "scale": scale if scale is not None else 1.0,
        })
    return matches


def match_rmsnorm_patterns(jaxpr) -> List[dict]:
    """RMSNorm chains as the models emit them:

        x32 = convert(x); var = mean(square(x32), -1, keepdims=True)
        y = (x32 * rsqrt(var + eps)).astype(x.dtype) * w

    i.e. [convert] -> square -> reduce_sum -> broadcast -> div(n) ->
    add(eps) -> rsqrt -> mul -> [convert] -> mul(broadcast(w)).
    Rewritten to the in-tree Pallas fused_rms_norm kernel."""
    eqns = jaxpr.eqns
    producer: Dict[Any, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.outvars:
            producer[v] = i
    uses = _build_use_map(jaxpr)

    def prod(v):
        return eqns[producer[v]] if v in producer else None

    matches = []
    for i, eqn in enumerate(eqns):
        if eqn.primitive.name != "rsqrt":
            continue
        chain: Set[int] = {i}
        e_add = prod(eqn.invars[0])
        if e_add is None or e_add.primitive.name != "add":
            continue
        lit = [x for x in e_add.invars if isinstance(x, jcore.Literal)]
        varin = [x for x in e_add.invars
                 if not isinstance(x, jcore.Literal)]
        if len(lit) != 1 or len(varin) != 1:
            continue
        eps = float(lit[0].val)
        chain.add(producer[eqn.invars[0]])  # the add itself
        chain.add(producer[varin[0]])
        e_div = prod(varin[0])
        if e_div is None or e_div.primitive.name != "div":
            continue
        if not isinstance(e_div.invars[1], jcore.Literal):
            continue
        chain.add(producer[e_div.invars[0]])
        e_bc = prod(e_div.invars[0])
        if e_bc is None or e_bc.primitive.name != "broadcast_in_dim":
            continue
        chain.add(producer[e_bc.invars[0]])
        e_sum = prod(e_bc.invars[0])
        if e_sum is None or e_sum.primitive.name != "reduce_sum":
            continue
        chain.add(producer[e_sum.invars[0]])
        e_sq = prod(e_sum.invars[0])
        if e_sq is None or e_sq.primitive.name != "square":
            continue
        x32_var = e_sq.invars[0]
        e_conv = prod(x32_var)
        if e_conv is not None and \
                e_conv.primitive.name == "convert_element_type":
            x_var = e_conv.invars[0]
            chain.add(producer[x32_var])
        else:
            x_var = x32_var
        if float(e_div.invars[1].val) != float(x_var.aval.shape[-1]):
            continue  # the mean divisor must be the hidden dim
        # forward: rsqrt -> mul(x32, .) -> [convert] -> mul(., bcast(w))
        r_uses = uses.get(eqn.outvars[0], [])
        if len(r_uses) != 1 or r_uses[0] == -1:
            continue
        e_mul = eqns[r_uses[0]]
        if e_mul.primitive.name != "mul":
            continue
        other = [v for v in e_mul.invars if v is not eqn.outvars[0]]
        if len(other) != 1 or _follow_converts_back(
                eqns, producer, other[0], chain) is not \
                _follow_converts_back(eqns, producer, x32_var, set()):
            continue
        chain.add(r_uses[0])
        nv = e_mul.outvars[0]
        u2 = uses.get(nv, [])
        if len(u2) != 1 or u2[0] == -1:
            continue
        e_next = eqns[u2[0]]
        if e_next.primitive.name == "convert_element_type":
            chain.add(u2[0])
            nv = e_next.outvars[0]
            u2 = uses.get(nv, [])
            if len(u2) != 1 or u2[0] == -1:
                continue
            e_next = eqns[u2[0]]
        if e_next.primitive.name != "mul":
            continue
        w_side = [v for v in e_next.invars if v is not nv]
        if len(w_side) != 1:
            continue
        wv = w_side[0]
        e_wb = prod(wv)
        if e_wb is not None and e_wb.primitive.name == "broadcast_in_dim":
            chain.add(producer[wv])
            wv = e_wb.invars[0]
        if len(wv.aval.shape) != 1 or \
                wv.aval.shape[0] != x_var.aval.shape[-1]:
            continue
        final = u2[0]
        kept = _external_uses_keep(eqns, uses, producer, chain, final)
        if kept is None:
            continue
        matches.append({"pattern": "rmsnorm", "final": final,
                        "chain": kept, "x": x_var, "w": wv, "eps": eps})
    return matches


def match_swiglu_patterns(jaxpr) -> List[dict]:
    """silu(gate) * up -> the in-tree Pallas swiglu kernel. jax.nn.silu
    traces as a pjit[name=silu] call eqn, so the anchor is exact."""
    eqns = jaxpr.eqns
    producer: Dict[Any, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.outvars:
            producer[v] = i
    uses = _build_use_map(jaxpr)
    matches = []
    for i, eqn in enumerate(eqns):
        if eqn.primitive.name != "mul":
            continue
        for a, b in ((eqn.invars[0], eqn.invars[1]),
                     (eqn.invars[1], eqn.invars[0])):
            if isinstance(a, jcore.Literal) or a not in producer:
                continue
            e_silu = eqns[producer[a]]
            if e_silu.primitive.name not in ("pjit", "jit", "closed_call") \
                    or e_silu.params.get("name") != "silu":
                continue
            if isinstance(b, jcore.Literal) or \
                    a.aval.shape != b.aval.shape:
                continue
            chain = {producer[a]}
            kept = _external_uses_keep(eqns, uses, producer, chain, i)
            if kept is None:
                continue
            matches.append({"pattern": "swiglu", "final": i,
                            "chain": kept, "gate": e_silu.invars[0],
                            "up": b})
            break
    return matches


def _follow_converts_back(eqns, producer, var, chain: Set[int]):
    """Resolve through convert_element_type producers, adding them to
    chain; returns the root var."""
    while var in producer and \
            eqns[producer[var]].primitive.name == "convert_element_type":
        chain.add(producer[var])
        var = eqns[producer[var]].invars[0]
    return var


def _external_uses_keep(eqns, uses, producer, chain: Set[int],
                        final: int) -> Optional[Set[int]]:
    """Drop chain eqns whose outputs escape (they must stay materialized,
    plus their upstream chain producers). None = nothing left to skip
    (fusing would be a pessimization)."""
    keep: Set[int] = set()
    for idx in chain:
        for ov in eqns[idx].outvars:
            ext = [u for u in uses.get(ov, [])
                   if u != final and u not in chain]
            if ext:
                keep.add(idx)
    changed = True
    while changed:
        changed = False
        for idx in list(keep):
            for iv in eqns[idx].invars:
                if isinstance(iv, jcore.Literal):
                    continue
                p = producer.get(iv)
                if p is not None and p in chain and p not in keep:
                    keep.add(p)
                    changed = True
    remaining = chain - keep
    return remaining if remaining else None


def match_bias_residual_ln_patterns(jaxpr) -> List[dict]:
    """[x + bcast(bias)] + residual -> layer_norm chain (the eval-mode
    form of the reference's fused_bias_dropout_residual_layer_norm —
    dropout is identity at inference). Rewritten to the one-kernel
    ops.fused.fused_bias_residual_layer_norm.

    Chain (as incubate functional traces it):
        h = add([add(x, bcast(b))], r)
        mu = div(bcast(reduce_sum(h)), N)
        var = div(bcast(reduce_sum(square(sub(h, mu)))), N)
        y = mul(sub(h, mu), rsqrt(var + eps)) [* bcast(w)] [+ bcast(lb)]
    """
    eqns = jaxpr.eqns
    producer: Dict[Any, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.outvars:
            producer[v] = i
    uses = _build_use_map(jaxpr)

    def prod(v):
        # Literals are unhashable — they also never have producers
        if isinstance(v, jcore.Literal):
            return None
        return eqns[producer[v]] if v in producer else None

    def is_mean_of(var, chain):
        """div(bcast(reduce_sum(src)), N) -> (src, N) or None."""
        e_div = prod(var)
        if e_div is None or e_div.primitive.name != "div" or \
                not isinstance(e_div.invars[1], jcore.Literal):
            return None
        e_bc = prod(e_div.invars[0])
        if e_bc is None or e_bc.primitive.name != "broadcast_in_dim":
            return None
        e_sum = prod(e_bc.invars[0])
        if e_sum is None or e_sum.primitive.name != "reduce_sum":
            return None
        chain.update({producer[var], producer[e_div.invars[0]],
                      producer[e_bc.invars[0]]})
        return e_sum.invars[0], float(e_div.invars[1].val)

    matches = []
    for i, eqn in enumerate(eqns):
        if eqn.primitive.name != "rsqrt":
            continue
        chain: Set[int] = {i}
        e_add = prod(eqn.invars[0])
        if e_add is None or e_add.primitive.name != "add":
            continue
        lit = [x for x in e_add.invars if isinstance(x, jcore.Literal)]
        varin = [x for x in e_add.invars if not isinstance(x, jcore.Literal)]
        if len(lit) != 1 or len(varin) != 1:
            continue
        eps = float(lit[0].val)
        chain.add(producer[eqn.invars[0]])
        got = is_mean_of(varin[0], chain)
        if got is None:
            continue
        sq_var, n = got
        e_sq = prod(sq_var)
        if e_sq is None or e_sq.primitive.name != "square":
            continue
        chain.add(producer[sq_var])
        e_sub = prod(e_sq.invars[0])
        if e_sub is None or e_sub.primitive.name != "sub":
            continue
        chain.add(producer[e_sq.invars[0]])
        h_var, mu_var = e_sub.invars
        got2 = is_mean_of(mu_var, chain)
        if got2 is None or got2[0] is not h_var or got2[1] != n:
            continue
        if float(n) != float(h_var.aval.shape[-1]):
            continue
        # forward: mul(sub(h, mu), rsqrt) — the sub may be a distinct eqn
        r_uses = uses.get(eqn.outvars[0], [])
        if len(r_uses) != 1 or r_uses[0] == -1:
            continue
        e_mul = eqns[r_uses[0]]
        if e_mul.primitive.name != "mul":
            continue
        other = [v for v in e_mul.invars if v is not eqn.outvars[0]]
        if not other or isinstance(other[0], jcore.Literal):
            continue
        e_q = prod(other[0])
        if e_q is None or e_q.primitive.name != "sub" or \
                e_q.invars[0] is not h_var or e_q.invars[1] is not mu_var:
            continue
        chain.add(producer[other[0]])
        chain.add(r_uses[0])
        final = r_uses[0]
        nv = e_mul.outvars[0]

        def bcast_vec(var):
            if isinstance(var, jcore.Literal):
                return None, None
            e = prod(var)
            if e is not None and e.primitive.name == "broadcast_in_dim" \
                    and not isinstance(e.invars[0], jcore.Literal) \
                    and len(e.invars[0].aval.shape) == 1:
                return e.invars[0], producer[var]
            return None, None

        w_var = lnb_var = None
        u2 = uses.get(nv, [])
        if len(u2) == 1 and u2[0] != -1 and \
                eqns[u2[0]].primitive.name == "mul":
            e_w = eqns[u2[0]]
            side = [v for v in e_w.invars if v is not nv]
            wv, widx = bcast_vec(side[0]) if side else (None, None)
            if wv is not None:
                w_var = wv
                chain.add(u2[0])
                chain.add(widx)
                final = u2[0]
                nv = e_w.outvars[0]
                u2 = uses.get(nv, [])
        if len(u2) == 1 and u2[0] != -1 and \
                eqns[u2[0]].primitive.name == "add":
            e_b = eqns[u2[0]]
            side = [v for v in e_b.invars if v is not nv]
            bv, bidx = bcast_vec(side[0]) if side else (None, None)
            if bv is not None:
                lnb_var = bv
                chain.add(u2[0])
                chain.add(bidx)
                final = u2[0]
        # upstream: h = add(g, r); g = add(x, bcast(bias)) optional
        e_h = prod(h_var)
        if e_h is None or e_h.primitive.name != "add":
            continue  # need at least the residual add to beat plain LN
        a0, a1 = e_h.invars
        if isinstance(a0, jcore.Literal) or isinstance(a1, jcore.Literal):
            continue
        if a0.aval.shape != a1.aval.shape:
            continue
        chain.add(producer[h_var])
        x_var, r_var, b_var = a0, a1, None
        e_g = prod(a0)
        if e_g is not None and e_g.primitive.name == "add":
            bv, bidx = bcast_vec(e_g.invars[1])
            if bv is None:
                bv, bidx = bcast_vec(e_g.invars[0])
                other_side = e_g.invars[1]
            else:
                other_side = e_g.invars[0]
            if bv is not None:
                x_var, r_var, b_var = other_side, a1, bv
                chain.add(producer[a0])
                chain.add(bidx)
        chain.discard(final)  # the final eqn is replaced, not deleted
        kept = _external_uses_keep(eqns, uses, producer, chain, final)
        if kept is None:
            continue
        matches.append({"pattern": "bias_residual_ln", "final": final,
                        "chain": kept, "x": x_var, "residual": r_var,
                        "bias": b_var, "w": w_var, "lnb": lnb_var,
                        "eps": eps})
    return matches


def match_moe_dispatch_patterns(jaxpr) -> List[dict]:
    """The GShard gate's dispatch/combine einsum pair:

        dispatch = dot_general(keep, ohk)   # tke,tkc->tec
        combine  = dot_general(keep, gv*ohk)

    (gv*ohk traces as a batch-batch dot_general). Both contractions plus
    the scale run in ONE Pallas kernel
    (ops.fused.fused_moe_dispatch_combine) — a two-output match."""
    eqns = jaxpr.eqns
    producer: Dict[Any, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.outvars:
            producer[v] = i
    uses = _build_use_map(jaxpr)
    pair_dn = (((1,), (1,)), ((0,), (0,)))
    scale_dn = (((), ()), ((0, 1), (0, 1)))
    matches = []
    for i, eqn in enumerate(eqns):
        # `combine`: its rhs comes from the gv scale dot
        if eqn.primitive.name != "dot_general" or \
                eqn.params.get("dimension_numbers") != pair_dn:
            continue
        keep_var, bp_var = eqn.invars
        if isinstance(bp_var, jcore.Literal) or bp_var not in producer:
            continue
        e_scale = eqns[producer[bp_var]]
        if e_scale.primitive.name != "dot_general" or \
                e_scale.params.get("dimension_numbers") != scale_dn:
            continue
        gv_var, ohk_var = e_scale.invars
        if len(gv_var.aval.shape) != 2:
            continue
        # find the sibling dispatch dot: same keep, rhs = ohk directly
        disp_idx = None
        for j, ej in enumerate(eqns):
            if j == i or ej.primitive.name != "dot_general":
                continue
            if ej.params.get("dimension_numbers") != pair_dn:
                continue
            if ej.invars[0] is keep_var and ej.invars[1] is ohk_var:
                disp_idx = j
                break
        if disp_idx is None:
            continue
        # the fused kernel executes at the FIRST final reached and reads
        # gv there — gv must already be computed at that point (a user
        # program may order the gate-value math after the dispatch dot)
        if not isinstance(gv_var, jcore.Literal) and \
                producer.get(gv_var, -1) > min(disp_idx, i):
            continue
        # the scale dot is interior; its output must feed only `combine`
        if uses.get(bp_var, []) != [i]:
            continue
        matches.append({
            "pattern": "moe_dispatch", "final": i,
            "finals": {disp_idx: 0, i: 1},
            "chain": {producer[bp_var]},
            "keep": keep_var, "ohk": ohk_var, "gv": gv_var,
        })
    return matches


def _flash_eligible_shapes(q_aval, k_aval) -> bool:
    """Shapes the Pallas kernel accepts. Off-TPU the pass still fuses
    (substituting the reference composite) so the rewrite is testable on
    the simulated-mesh CI backend."""
    from ..ops.flash_attention import (_largest_dividing_block,
                                       _tpu_flash_available)
    if len(q_aval.shape) != 4:
        return False
    B, H, S, D = q_aval.shape
    Sk = k_aval.shape[2]
    if not _tpu_flash_available():
        return True  # reference-composite substitution path
    return (S == Sk and _largest_dividing_block(S) > 0
            and ((D <= 128 and D % 64 == 0) or D % 128 == 0))


def _exec_sdpa(m, read):
    q, k, v = read(m["q"]), read(m["k"]), read(m["v"])
    from ..ops.flash_attention import (_flash_block_sizes,
                                       _tpu_flash_available,
                                       sdpa_reference)
    if _tpu_flash_available():
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as _pallas_flash)
        return _pallas_flash(
            q, k, v, causal=False, sm_scale=m["scale"],
            block_sizes=_flash_block_sizes(q.shape[2], k.shape[2]))
    return jnp.swapaxes(sdpa_reference(
        jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
        jnp.swapaxes(v, 1, 2), scale=m["scale"]), 1, 2)


def _exec_rmsnorm(m, read):
    from ..ops.fused import fused_rms_norm
    return fused_rms_norm(read(m["x"]), read(m["w"]), eps=m["eps"])


def _exec_swiglu(m, read):
    from ..ops.fused import swiglu as _swiglu
    return _swiglu(read(m["gate"]), read(m["up"]))


def _exec_brln(m, read):
    from ..ops.fused import fused_bias_residual_layer_norm
    return fused_bias_residual_layer_norm(
        read(m["x"]), read(m["residual"]),
        bias=None if m["bias"] is None else read(m["bias"]),
        weight=None if m["w"] is None else read(m["w"]),
        ln_bias=None if m["lnb"] is None else read(m["lnb"]),
        eps=m["eps"])


def _exec_moe_dispatch(m, read):
    from ..ops.fused import fused_moe_dispatch_combine
    return tuple(fused_moe_dispatch_combine(
        read(m["keep"]), read(m["ohk"]), read(m["gv"])))


def _sdpa_shape_ok(m):
    return _flash_eligible_shapes(m["q"].aval, m["k"].aval)


def _lane_ok(m, key):
    # the Pallas elementwise kernels want a 128-multiple (or tiny-test
    # interpret) lane dim; off-TPU interpret mode takes anything
    import jax as _jax
    if _jax.default_backend() != "tpu":
        return True
    return m[key].aval.shape[-1] % 128 == 0


# The CINN-parity pattern table (ref: paddle/cinn/operator_fusion/ —
# pattern registry + replace-with-kernel): matcher, eligibility filter,
# executor. Extending the pass = adding a row.
def _moe_lane_ok(m):
    import jax as _jax
    if _jax.default_backend() != "tpu":
        return True
    # kernel block layout: keep [.,k,E], ohk [.,k,C], outs [.,E,C]
    E = m["keep"].aval.shape[-1]
    C = m["ohk"].aval.shape[-1]
    return E % 128 == 0 and C % 128 == 0


PATTERNS = {
    "sdpa": (match_sdpa_patterns, _sdpa_shape_ok, _exec_sdpa),
    "rmsnorm": (match_rmsnorm_patterns,
                lambda m: _lane_ok(m, "x"), _exec_rmsnorm),
    "swiglu": (match_swiglu_patterns,
               lambda m: _lane_ok(m, "gate"), _exec_swiglu),
    "bias_residual_ln": (match_bias_residual_ln_patterns,
                         lambda m: _lane_ok(m, "x"), _exec_brln),
    "moe_dispatch": (match_moe_dispatch_patterns, _moe_lane_ok,
                     _exec_moe_dispatch),
}


def _run_fused(closed, matches, consts, *flat_args):
    """Interpret the jaxpr, executing matched chains as fused-kernel
    calls and skipping their interior equations."""
    jaxpr = closed.jaxpr
    env: Dict[Any, Any] = {}

    def read(v):
        return v.val if isinstance(v, jcore.Literal) else env[v]

    def write(v, val):
        env[v] = val

    for v, c in zip(jaxpr.constvars, consts):
        write(v, c)
    for v, a in zip(jaxpr.invars, flat_args):
        write(v, a)

    # single-output matches: {"final": i}; multi-output matches carry
    # {"finals": {eqn_idx: tuple_position}} (e.g. moe_dispatch emits
    # dispatch AND combine from one kernel call)
    by_final: Dict[int, Any] = {}
    for m in matches:
        for fi in m.get("finals", {m["final"]: None}):
            by_final[fi] = m
    skip: Set[int] = set()
    for m in matches:
        skip |= m["chain"]

    fused_cache: Dict[int, Any] = {}

    for i, eqn in enumerate(jaxpr.eqns):
        if i in skip:
            continue
        if i in by_final:
            m = by_final[i]
            finals = m.get("finals")
            if finals is None:
                out = PATTERNS[m["pattern"]][2](m, read)
            else:
                if id(m) not in fused_cache:
                    fused_cache[id(m)] = PATTERNS[m["pattern"]][2](m, read)
                out = fused_cache[id(m)][finals[i]]
            write(eqn.outvars[0], out.astype(eqn.outvars[0].aval.dtype))
            continue
        vals = [read(x) for x in eqn.invars]
        sub = eqn.primitive.bind(*vals, **eqn.params)
        if eqn.primitive.multiple_results:
            for ov, o in zip(eqn.outvars, sub):
                write(ov, o)
        else:
            write(eqn.outvars[0], sub)
    return [read(v) for v in jaxpr.outvars]


def fuse(fn):
    """Transform: rewrite recognizable SDPA chains in `fn`'s traced program
    into Pallas flash-attention kernel calls (the CINN 'replace fused group
    with a JIT kernel op' step). Falls back to `fn` untouched when nothing
    matches or tracing is not possible."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        try:
            # one trace gives both the jaxpr and the output pytree
            closed, out_shape = jax.make_jaxpr(
                lambda *a: fn(*a, **kwargs), return_shape=True)(*args)
        except Exception:
            return fn(*args, **kwargs)
        matches = []
        claimed: Set[int] = set()
        for name, (matcher, eligible, _) in PATTERNS.items():
            for m in matcher(closed.jaxpr):
                if not eligible(m):
                    continue
                span = m["chain"] | set(m.get("finals", {m["final"]: 0}))
                if span & claimed:
                    continue  # first pattern wins on overlapping regions
                claimed |= span
                matches.append(m)
        flat, _ = jax.tree_util.tree_flatten(args)
        # no-match: interpret the already-traced jaxpr rather than
        # re-tracing fn a second time
        outs = _run_fused(closed, matches, closed.consts, *flat)
        out_tree = jax.tree_util.tree_structure(out_shape)
        return jax.tree_util.tree_unflatten(out_tree, outs)

    return wrapped
