"""paddle.quantization parity (ref: python/paddle/quantization/ — QAT/PTQ
framework with quanter/observer configs; python/paddle/nn/quant weight-only
layers; SURVEY §2.2 quantization row).

TPU-native: observers collect ranges in plain jax; fake-quant is a
straight-through estimator; the deploy path converts Linear layers to
weight-only int8 backed by the Pallas dequant-matmul kernel
(paddle_tpu.ops.quant)."""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from .. import nn

__all__ = ["AbsmaxObserver", "FakeQuanterWithAbsMax", "QuantConfig", "QAT",
           "PTQ", "QuantedLinear", "quanted_linear_from"]


class AbsmaxObserver:
    """Tracks running absmax for activation scales (ref: observers/abs_max)."""

    def __init__(self, quant_bits: int = 8):
        self.quant_bits = quant_bits
        self.absmax = 0.0

    def observe(self, x):
        xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        self.absmax = max(self.absmax, float(jnp.max(jnp.abs(xa))))
        return x

    def scale(self) -> float:
        qmax = 2 ** (self.quant_bits - 1) - 1
        return self.absmax / qmax if self.absmax else 1.0


class FakeQuanterWithAbsMax(nn.Layer):
    """QAT fake-quant with straight-through gradients (ref:
    quanters/abs_max.py FakeQuanterWithAbsMaxObserver)."""

    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self.quant_bits = quant_bits

    def forward(self, x):
        qmax = 2 ** (self.quant_bits - 1) - 1

        def impl(a):
            scale = jnp.max(jnp.abs(a)) / qmax
            scale = jnp.maximum(scale, 1e-8)
            q = jnp.clip(jnp.round(a / scale), -qmax, qmax) * scale
            # straight-through: forward q, backward identity
            return a + jax.lax.stop_gradient(q - a)
        return apply("fake_quant_absmax", impl, [x])


class QuantConfig:
    """ref: paddle.quantization.QuantConfig — maps layer types/names to
    quanters."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._type_configs: Dict[type, dict] = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        for t in (layer_type if isinstance(layer_type, (list, tuple))
                  else [layer_type]):
            self._type_configs[t] = {"activation": activation,
                                     "weight": weight}

    def config_for(self, layer):
        for t, cfg in self._type_configs.items():
            if isinstance(layer, t):
                return cfg
        if self.activation or self.weight:
            return {"activation": self.activation, "weight": self.weight}
        return None


class _QATLinear(nn.Layer):
    def __init__(self, inner: nn.Linear, a_quanter, w_quanter):
        super().__init__()
        self.inner = inner
        self.a_q = a_quanter
        self.w_q = w_quanter

    def forward(self, x):
        if self.a_q is not None:
            x = self.a_q(x)
        w = self.inner.weight
        if self.w_q is not None:
            w = self.w_q(w)
        from ..nn import functional as F
        return F.linear(x, w, self.inner.bias)


class QAT:
    """Quantization-aware training flow (ref: paddle.quantization.QAT)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace: bool = False):
        for name, sub in list(model.named_sublayers()):
            for cname, child in list(sub.__dict__["_sub_layers"].items()):
                cfg = self.config.config_for(child)
                if cfg and isinstance(child, nn.Linear):
                    a_q = cfg["activation"]() if cfg["activation"] else None
                    w_q = cfg["weight"]() if cfg["weight"] else None
                    sub.add_sublayer(cname, _QATLinear(child, a_q, w_q))
        # top-level children too
        for cname, child in list(model.__dict__["_sub_layers"].items()):
            cfg = self.config.config_for(child)
            if cfg and isinstance(child, nn.Linear):
                a_q = cfg["activation"]() if cfg["activation"] else None
                w_q = cfg["weight"]() if cfg["weight"] else None
                model.add_sublayer(cname, _QATLinear(child, a_q, w_q))
        return model


class QuantedLinear(nn.Layer):
    """Deployed weight-only int8 linear over the Pallas dequant-matmul."""

    def __init__(self, qweight, scale, bias=None):
        super().__init__()
        self.qweight = qweight
        self.scale = scale
        self.bias = bias

    def forward(self, x):
        from ..incubate.nn.functional import weight_only_linear
        return weight_only_linear(x, self.qweight, bias=self.bias,
                                  weight_scale=self.scale)


def quanted_linear_from(linear: nn.Linear) -> QuantedLinear:
    from ..ops.quant import weight_quantize
    qw, sc = weight_quantize(linear.weight._data)
    return QuantedLinear(Tensor(qw), Tensor(sc), linear.bias)


class PTQ:
    """Post-training quantization flow (ref: paddle.quantization.PTQ):
    observe activations on calibration batches, then convert Linears to
    weight-only int8."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()
        self.observers: Dict[str, AbsmaxObserver] = {}

    def quantize(self, model, inplace: bool = False):
        self._hooks = []
        for name, sub in model.named_sublayers():
            if isinstance(sub, nn.Linear):
                obs = AbsmaxObserver()
                self.observers[name] = obs

                def mk(o):
                    def hook(layer, inputs):
                        o.observe(inputs[0])
                        return None
                    return hook
                self._hooks.append(sub.register_forward_pre_hook(mk(obs)))
        return model

    def convert(self, model, inplace: bool = False):
        for h in getattr(self, "_hooks", []):
            h.remove()
        def convert_children(parent):
            for cname, child in list(parent.__dict__["_sub_layers"].items()):
                if isinstance(child, nn.Linear):
                    parent.add_sublayer(cname, quanted_linear_from(child))
                else:
                    convert_children(child)
        convert_children(model)
        return model
