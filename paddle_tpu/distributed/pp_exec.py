"""Timetable-driven pipeline EXECUTOR: runs pp_schedule.Schedule
(FThenB / 1F1B / ZBH1) as one compiled SPMD program.

Reference parity: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py (1F1B runtime) + distributed/passes/
pipeline_scheduler_pass.py (ZBH1) — SURVEY §2.3 P6. The reference drives
these orders with an actor runtime and NCCL p2p; here the SAME validated
timetable (distributed/pp_schedule.py) is baked into a `lax.scan` over
ticks inside a `shard_map` over the `pp` mesh axis:

  - tick t, stage s executes exactly timeline[s][t]: F (forward one
    microbatch), B (backward-dgrad; at the last stage this also runs the
    loss head and seeds the cotangent), or W (deferred weight-grad — the
    ZBH1 split).
  - activations hop downstream and cotangents upstream via lax.ppermute,
    one message per tick, matching the schedule's 1-tick p2p latency
    model.
  - each stage keeps stage-INPUTS only (remat: B/W recompute the stage
    forward), in a ring buffer whose size is the schedule's peak-liveness
    bound (~n_stages) — NOT the microbatch count. This is 1F1B's memory
    point: GPipe's compiled autodiff stores M stage-inputs per stage, the
    executor stores ≤ bound(s) ≤ S+1.

Because forward and backward INTERLEAVE inside one program, outer
autodiff cannot drive it; `scheduled_pipeline_loss` therefore computes
all gradients in its (custom_vjp) forward pass and replays them, scaled,
in the backward rule — embedding and anything upstream of the pipeline
still differentiate normally through the returned d_microbatches.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .pipeline import PP_AXIS, _cpu_f32_upcast, _pp_shard_map
from .pp_schedule import Schedule

__all__ = ["scheduled_pipeline_loss", "schedule_buffer_bounds"]

_PHASES = {"F": 1, "B": 2, "W": 3}  # 0 = bubble


def _tables(schedule: Schedule):
    """timeline -> (phase[S,T], mb[S,T]) int32 numpy tables."""
    S, T = schedule.n_stages, schedule.n_ticks
    phase = np.zeros((S, T), np.int32)
    mb = np.zeros((S, T), np.int32)
    for s, row in enumerate(schedule.timeline):
        for t, op in enumerate(row):
            if op is not None:
                phase[s, t] = _PHASES[op.phase]
                mb[s, t] = op.mb
    return phase, mb


def _stage_intervals(schedule: Schedule):
    """Per-stage liveness intervals derived from the timetable — the ONE
    source both the buffer sizing and the slot-collision guard use.
    Yields (stage, {"in_buf": [(mb, start, end)], "cot_buf": ...,
    "w_buf": ...})."""
    S, M = schedule.n_stages, schedule.n_microbatches
    fin: Dict[Tuple[str, int, int], int] = {}
    start: Dict[Tuple[str, int, int], int] = {}
    for s, row in enumerate(schedule.timeline):
        for t, op in enumerate(row):
            if op is not None:
                fin[(op.phase, s, op.mb)] = t + 1
                start[(op.phase, s, op.mb)] = t
    for s in range(S):
        iv = {"in_buf": [], "cot_buf": [], "w_buf": []}
        for m in range(M):
            arr = fin[("F", s - 1, m)] if s > 0 else start[("F", s, m)]
            iv["in_buf"].append((m, arr, fin[("B", s, m)]))
            if s < S - 1:
                iv["cot_buf"].append((m, fin[("B", s + 1, m)],
                                      fin[("B", s, m)]))
            if schedule.split_w:
                iv["w_buf"].append((m, fin[("B", s, m)],
                                    fin[("W", s, m)]))
        yield s, iv


def schedule_buffer_bounds(schedule: Schedule) -> Dict[str, int]:
    """Peak liveness the executor must buffer, derived from the timetable:

    in_buf  — stage inputs: live from the producing stage's F (arrival)
              until this stage's B consumes them;
    cot_buf — cotangents: from downstream B until this stage's B;
    w_buf   — (ZBH1) retained (input, cotangent) pairs from B until W.

    For 1F1B these are O(n_stages); for FThenB in_buf is O(M) — the
    executor allocates what the schedule needs, so the memory claim is
    checkable per schedule. Buffers are PER DEVICE: max over stages.
    """
    def peak(intervals):
        events = []
        for _, a, b in intervals:
            events.append((a, 1))
            events.append((b, -1))
        live = best = 0
        for _, d in sorted(events, key=lambda e: (e[0], -e[1])):
            live += d
            best = max(best, live)
        return best
    out = {"in_buf": 0, "cot_buf": 1, "w_buf": 0}
    for _, iv in _stage_intervals(schedule):
        for name in out:
            out[name] = max(out[name], peak(iv[name]))
    if not schedule.split_w:
        out["w_buf"] = 0
    return out


def _check_slots(schedule: Schedule, K: int, KC: int, KW: int) -> None:
    """Simulate ring-buffer occupancy against the timetable: writing slot
    m % K while a DIFFERENT live microbatch occupies it is a hard error
    (would corrupt an activation). Guards the contiguous-window assumption
    the modulo slotting relies on."""
    def check(intervals, nslots, name, stage):
        occupied: Dict[int, Tuple[int, int]] = {}
        for m, a, b in sorted(intervals, key=lambda iv: iv[1]):
            slot = m % nslots
            if slot in occupied:
                m0, b0 = occupied[slot]
                if a < b0 and m0 != m:
                    raise AssertionError(
                        f"{name} slot collision at stage {stage}: mb {m} "
                        f"overwrites live mb {m0} (slots={nslots})")
            occupied[slot] = (m, b)
    sizes = {"in_buf": K, "cot_buf": KC, "w_buf": KW}
    for s, iv in _stage_intervals(schedule):
        for name, nslots in sizes.items():
            if name == "w_buf" and not schedule.split_w:
                continue
            check(iv[name], nslots, name, s)


def scheduled_pipeline_loss(schedule: Schedule, stage_fn: Callable,
                            head_fn: Callable, mesh: Mesh,
                            stacked_params: Dict[str, Any], head_params,
                            microbatches, labels, extra_args=()):
    """Execute `schedule` over the pp axis of `mesh`; returns the SUMMED
    loss (caller normalizes). Differentiable in (stacked_params,
    head_params, microbatches).

    stage_fn(local_params, x, *extra) -> y          (one stage's layers)
    head_fn(head_params, y, labels_mb) -> scalar    (last-stage loss head,
                                                     SUM over tokens)
    stacked_params: {name: [S, L/S, ...]}, dim 0 on pp.
    microbatches: [M, mb, ...] stage-0 inputs (already embedded).
    labels: [M, mb, ...] int labels per microbatch.
    """
    S = mesh.shape[PP_AXIS]
    M = schedule.n_microbatches
    if schedule.n_stages != S:
        raise ValueError(f"schedule has {schedule.n_stages} stages, "
                         f"mesh pp={S}")
    if schedule.n_chunks != 1:
        raise ValueError("scheduled executor supports n_chunks=1; use "
                         "spmd_pipeline_interleaved for VPP")
    if S == 1:
        raise ValueError("pp=1 needs no schedule; use spmd_pipeline")

    upcast = _cpu_f32_upcast(stacked_params, microbatches, extra_args)
    if upcast is not None:
        stacked_params, microbatches, extra_args, _ = upcast
        head_params = jax.tree.map(
            lambda v: v.astype(jnp.float32)
            if jnp.issubdtype(v.dtype, jnp.floating)
            and jnp.dtype(v.dtype).itemsize < 4 else v, head_params)

    phase_np, mb_np = _tables(schedule)
    bounds = schedule_buffer_bounds(schedule)
    K = bounds["in_buf"] + 1          # +1: write-before-read margin
    KC = bounds["cot_buf"] + 1
    KW = (bounds["w_buf"] + 1) if schedule.split_w else 1
    _check_slots(schedule, K, KC, KW)
    T = schedule.n_ticks
    phase_tab = jnp.asarray(phase_np)
    mb_tab = jnp.asarray(mb_np)
    down = [(i, (i + 1) % S) for i in range(S)]
    up = [((i + 1) % S, i) for i in range(S)]

    cdt = microbatches.dtype
    mb_shape = microbatches.shape[1:]

    def _f32_psum(x):
        return jax.lax.psum(x.astype(jnp.float32), PP_AXIS).astype(x.dtype)

    def per_device(params, head_p, mbs, labels_, *extra):
        local = {k: v[0] for k, v in params.items()}   # [L/S, ...]
        stage = jax.lax.axis_index(PP_AXIS)
        zero_mb = jnp.zeros(mb_shape, cdt)

        def stage_f(p, x):
            return stage_fn(p, x, *extra)

        def pv(a):
            """pvary, idempotent: no-op when already device-varying."""
            vma = getattr(jax.typeof(a), "vma", frozenset())
            return a if PP_AXIS in vma else jax.lax.pvary(a, PP_AXIS)
        # CRITICAL: vjp w.r.t. a pp-INVARIANT value makes shard_map insert
        # a psum_invariant collective to re-invariant the cotangent — and
        # a collective inside one lax.switch branch deadlocks devices that
        # took other branches. Mark the replicated head params varying
        # BEFORE any vjp; grads are psum'd once at the end instead.
        head_v = jax.tree.map(pv, head_p)
        carry0 = dict(
            in_buf=pv(jnp.zeros((K,) + mb_shape, cdt)),
            cot_buf=pv(jnp.zeros((KC,) + mb_shape, cdt)),
            wx_buf=pv(jnp.zeros((KW,) + mb_shape, cdt)),
            wg_buf=pv(jnp.zeros((KW,) + mb_shape, cdt)),
            dmbs=pv(jnp.zeros((M,) + mb_shape, cdt)),
            accp=jax.tree.map(
                lambda v: pv(jnp.zeros(v.shape, jnp.float32)), local),
            acch=jax.tree.map(
                lambda v: pv(jnp.zeros(v.shape, jnp.float32)), head_p),
            loss=pv(jnp.zeros((), jnp.float32)),
            fmsg=(pv(zero_mb), pv(jnp.zeros((), jnp.int32)),
                  pv(jnp.zeros((), jnp.bool_))),
            bmsg=(pv(zero_mb), pv(jnp.zeros((), jnp.int32)),
                  pv(jnp.zeros((), jnp.bool_))),
        )

        def tick(carry, t):
            c = dict(carry)
            # 1) deliver last tick's messages (1-tick p2p latency)
            fy, fm, fv = c["fmsg"]
            recv_f = jnp.logical_and(fv, stage > 0)
            c["in_buf"] = jax.lax.dynamic_update_index_in_dim(
                c["in_buf"],
                jnp.where(recv_f, fy, c["in_buf"][fm % K]), fm % K, 0)
            by, bm, bv = c["bmsg"]
            recv_b = jnp.logical_and(bv, stage < S - 1)
            c["cot_buf"] = jax.lax.dynamic_update_index_in_dim(
                c["cot_buf"],
                jnp.where(recv_b, by, c["cot_buf"][bm % KC]), bm % KC, 0)

            ph = phase_tab[stage, t]
            m = mb_tab[stage, t]
            no_f = (pv(zero_mb), pv(jnp.zeros((), jnp.int32)),
                    pv(jnp.zeros((), jnp.bool_)))
            no_b = (pv(zero_mb), pv(jnp.zeros((), jnp.int32)),
                    pv(jnp.zeros((), jnp.bool_)))

            def do_idle(c):
                return c, no_f, no_b

            def do_f(c):
                x = jnp.where(stage == 0, mbs[m], c["in_buf"][m % K])
                c = dict(c)
                c["in_buf"] = jax.lax.dynamic_update_index_in_dim(
                    c["in_buf"], x, m % K, 0)
                y = stage_f(local, x)
                fmsg = (y, m, stage < S - 1)
                return c, fmsg, no_b

            def do_b(c):
                x = c["in_buf"][m % K]
                last = stage == S - 1
                # ONE stage forward, residuals shared with the backward
                # (ZBH1 keeps the x-only vjp so W can be deferred)
                if schedule.split_w:
                    y, vjp_x = jax.vjp(lambda xx: stage_f(local, xx), x)
                else:
                    y, vjp_px = jax.vjp(stage_f, local, x)
                # the loss head runs ONLY on the last stage (lax.cond is
                # safe here: with head_v pre-pvary'd no branch contains a
                # collective); elsewhere the cotangent arrived upstream

                def head_branch():
                    loss, vjp = jax.vjp(
                        lambda hp_, y_: head_fn(hp_, y_, labels_[m]),
                        head_v, y)
                    dhp, dy_ = vjp(pv(jnp.ones((), loss.dtype)))
                    return loss.astype(jnp.float32), dy_, dhp

                def skip_branch():
                    return (pv(jnp.zeros((), jnp.float32)),
                            pv(jnp.zeros_like(y)),
                            jax.tree.map(lambda h: pv(jnp.zeros_like(h)),
                                         head_v))
                loss_l, dy_l, dhp_l = jax.lax.cond(last, head_branch,
                                                   skip_branch)
                dy = jnp.where(last, dy_l, c["cot_buf"][m % KC])
                c = dict(c)
                c["loss"] = c["loss"] + loss_l
                if schedule.split_w:
                    # ZBH1: dgrad now (critical path), wgrad deferred
                    (dx,) = vjp_x(dy)
                    c["wx_buf"] = jax.lax.dynamic_update_index_in_dim(
                        c["wx_buf"], x, m % KW, 0)
                    c["wg_buf"] = jax.lax.dynamic_update_index_in_dim(
                        c["wg_buf"], dy, m % KW, 0)
                else:
                    dp, dx = vjp_px(dy)
                    c["accp"] = jax.tree.map(
                        lambda a, g: a + g.astype(jnp.float32),
                        c["accp"], dp)
                c["acch"] = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32),
                    c["acch"], dhp_l)
                c["dmbs"] = jax.lax.dynamic_update_index_in_dim(
                    c["dmbs"],
                    jnp.where(stage == 0, dx, c["dmbs"][m]), m, 0)
                bmsg = (dx, m, stage > 0)
                return c, no_f, bmsg

            def do_w(c):
                x = c["wx_buf"][m % KW]
                dy = c["wg_buf"][m % KW]
                _, vjp_p = jax.vjp(lambda p: stage_f(p, x), local)
                (dp,) = vjp_p(dy)
                c = dict(c)
                c["accp"] = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), c["accp"], dp)
                return c, no_f, no_b

            c, fmsg, bmsg = jax.lax.switch(
                ph, [do_idle, do_f, do_b, do_w], c)
            # 3) rotate messages
            c["fmsg"] = (jax.lax.ppermute(fmsg[0], PP_AXIS, down),
                         jax.lax.ppermute(fmsg[1], PP_AXIS, down),
                         jax.lax.ppermute(fmsg[2], PP_AXIS, down))
            c["bmsg"] = (jax.lax.ppermute(bmsg[0], PP_AXIS, up),
                         jax.lax.ppermute(bmsg[1], PP_AXIS, up),
                         jax.lax.ppermute(bmsg[2], PP_AXIS, up))
            return c, None

        c, _ = jax.lax.scan(tick, carry0, jnp.arange(T))
        loss = jax.lax.psum(c["loss"], PP_AXIS)
        dmbs = _f32_psum(c["dmbs"])
        acch = jax.tree.map(lambda a: jax.lax.psum(a, PP_AXIS), c["acch"])
        accp = jax.tree.map(lambda a: a[None], c["accp"])  # [1, L/S, ...]
        return loss, accp, acch, dmbs

    param_specs = {k: P(PP_AXIS, *([None] * (v.ndim - 1)))
                   for k, v in stacked_params.items()}
    head_specs = jax.tree.map(lambda v: P(*([None] * jnp.ndim(v))),
                              head_params)
    mb_spec = P(*([None] * microbatches.ndim))
    lab_spec = P(*([None] * labels.ndim))
    extra_specs = tuple(P(*([None] * jnp.ndim(e))) for e in extra_args)

    fn = _pp_shard_map(
        per_device, mesh,
        in_specs=(param_specs, head_specs, mb_spec, lab_spec)
        + extra_specs,
        out_specs=(P(), param_specs, head_specs, mb_spec))

    pdt = {k: v.dtype for k, v in stacked_params.items()}
    hdt = jax.tree.map(lambda v: v.dtype, head_params)

    @jax.custom_vjp
    def run(sp, hp, mbs):
        loss, _, _, _ = jax.jit(fn)(sp, hp, mbs, labels, *extra_args)
        return loss

    def run_fwd(sp, hp, mbs):
        loss, accp, acch, dmbs = jax.jit(fn)(sp, hp, mbs, labels,
                                             *extra_args)
        accp = {k: v.astype(pdt[k]) for k, v in accp.items()}
        acch = jax.tree.map(lambda v, d: v.astype(d), acch, hdt)
        return loss, (accp, acch, dmbs)

    def run_bwd(res, g):
        accp, acch, dmbs = res
        scale = lambda v: (g * v.astype(jnp.float32)).astype(v.dtype)
        return (jax.tree.map(scale, accp), jax.tree.map(scale, acch),
                scale(dmbs))

    run.defvjp(run_fwd, run_bwd)
    return run(stacked_params, head_params, microbatches)
