"""PE rule family — grid memory-effects lane (ISSUE 19).

PE501  write-write overlap: an output block revisited along a grid axis
       that is not declared "arbitrary" in dimension_semantics.
PE502  read-after-donated-write: kernel re-reads a donated input after a
       store to its input_output_aliases partner (same buffer on TPU).
PE503  unguarded accumulator: a scratch/revisited-output ref read back
       without a sound (first-step-guarded or preceding unconditional)
       init store.
PE504  in-kernel scatter overlap: a dynamic (pl.dslice) store whose
       disjointness across grid steps cannot be proven — only the
       width-1 per-step-table form (the paged-append contract) passes;
       proven scatters surface as info under --strict.
PE505  fusion legality: PF404 candidates and registered compositions
       whose member effects compose without PE501-PE504 hazards get a
       "legal" info verdict; a hazard (e.g. read/write inversion of the
       leading index component) is an error naming the refs.
PE506  write-side cost drift: effects-model write bytes vs the
       costmodel's declared bytes_written, at the PF406 tolerance.

All checks run on :mod:`effectsmodel`; sites whose structure does not
resolve opt out (degrade to unknown, never guess).
"""

from __future__ import annotations

from typing import List

from . import effectsmodel as em
from . import kernelmodel as km
from . import vmemmodel as vm
from .callgraph import PackageIndex
from .model import Config, Finding, register_rule

register_rule(
    "PE501",
    "output block written by multiple grid steps without an "
    "\"arbitrary\" dimension_semantics declaration (write-write race)",
    severity="error", module=__name__)
register_rule(
    "PE502",
    "kernel re-reads a donated (input_output_aliases) argument after "
    "an aliased store — the read observes the in-place write",
    severity="error", module=__name__)
register_rule(
    "PE503",
    "accumulator on a revisiting grid axis lacks a sound init "
    "(@pl.when(first-step) seed or preceding unconditional store)",
    severity="error", module=__name__)
register_rule(
    "PE504",
    "in-kernel dynamic scatter whose destination disjointness across "
    "grid steps cannot be proven from the index expressions",
    severity="error", module=__name__)
register_rule(
    "PE505",
    "fusion-legality verdict for PF404 candidates and registered "
    "compositions: member effects must compose without PE501-PE504 "
    "hazards (legal verdicts are info; hazards are errors)",
    severity="info", module=__name__)
register_rule(
    "PE506",
    "effects-model write bytes drift vs costmodel bytes_written "
    "(kernel writes blocks the cost model does not charge)",
    severity="warning", module=__name__)

_EFFECT_RULES = ("PE501", "PE502", "PE503", "PE504")


def _finding(rule: str, eff: em.KernelEffects, h: dict,
             severity: str) -> Finding:
    site = eff.site
    return Finding(
        rule=rule, severity=severity, path=site.mi.rel,
        line=h.get("line", site.line), col=h.get("col", 0),
        qualname=site.qualname, message=h["message"],
        hint=h.get("hint", ""), detail=h["detail"])


def _pe505(index: PackageIndex) -> List[Finding]:
    out: List[Finding] = []
    sites = vm.canonical_sites(index)
    for v in em.compose_verdicts(index):
        # anchor at the producer/first-member site when it resolved
        qn = vm._CHAIN_SITE.get(v.get("producer")
                                or (v.get("members") or [""])[0])
        site = sites.get(qn) if qn else None
        path = site.mi.rel if site else "paddle_tpu/ops"
        line = site.line if site else 0
        qual = site.qualname if site else (qn or v["candidate"])
        if v["verdict"] == "hazard":
            out.append(Finding(
                rule="PE505", severity="error", path=path, line=line,
                col=0, qualname=qual,
                message=f"fusion candidate {v['candidate']} is NOT "
                        f"legal: " + "; ".join(v["hazards"]),
                hint="fix the member hazard (or re-tile the seam) "
                     "before fusing; see docs/ANALYSIS.md PE505",
                detail=f"fusehazard:{v['candidate']}"))
        elif v["verdict"] == "legal":
            out.append(Finding(
                rule="PE505", severity="info", path=path, line=line,
                col=0, qualname=qual,
                message=f"fusion candidate {v['candidate']} is legal: "
                        + "; ".join(v["notes"]),
                detail=f"fuselegal:{v['candidate']}"))
        else:
            out.append(Finding(
                rule="PE505", severity="info", path=path, line=line,
                col=0, qualname=qual,
                message=f"fusion candidate {v['candidate']}: no "
                        f"verdict — " + "; ".join(v["notes"]),
                detail=f"fuseunknown:{v['candidate']}"))
    return out


def _pe506(index: PackageIndex) -> List[Finding]:
    out: List[Finding] = []
    for rec in em.derive_write_bytes(index):
        if rec.get("status") != "drift":
            continue
        out.append(Finding(
            rule="PE506", severity="warning", path=rec["path"],
            line=rec["line"], col=0, qualname=rec["qualname"],
            message=f"effects-model write bytes for "
                    f"`{rec['kernel']}` ({rec['derived']:,}) drift "
                    f"{rec['rel_err']:.1%} from "
                    f"costmodel.bytes_written ({rec['expected']:,}) "
                    f"at the canonical shape",
            hint="the kernel writes blocks the cost model does not "
                 "charge (or vice versa); update "
                 "observability/costmodel.py or the out_specs",
            detail=f"wdrift:{rec['kernel']}"))
    return out


def run(index: PackageIndex, cfg: Config) -> List[Finding]:
    wanted = [r for r in ("PE501", "PE502", "PE503", "PE504", "PE505",
                          "PE506") if cfg.wants(r)]
    if not wanted:
        return []
    findings: List[Finding] = []
    if any(r in wanted for r in _EFFECT_RULES):
        for eff in em.collect_effects(index):
            if cfg.wants("PE501"):
                for h in em.ww_hazards(eff):
                    findings.append(_finding("PE501", eff, h, "error"))
            if cfg.wants("PE502"):
                for h in em.alias_read_hazards(eff):
                    findings.append(_finding("PE502", eff, h, "error"))
            if cfg.wants("PE503"):
                for h in em.accumulator_hazards(eff):
                    findings.append(_finding("PE503", eff, h, "error"))
            if cfg.wants("PE504"):
                errors, notes = em.scatter_hazards(eff)
                for h in errors:
                    findings.append(_finding("PE504", eff, h, "error"))
                for h in notes:
                    findings.append(_finding("PE504", eff, h, "info"))
    if cfg.wants("PE505"):
        findings.extend(_pe505(index))
    if cfg.wants("PE506"):
        findings.extend(_pe506(index))
    return findings
