"""Global radix prefix cache over the paged KV pool.

A trie keyed on token prefixes at PAGE granularity: each node is one
FULL page of `page_size` tokens, its edge key is that page's token
tuple, and its payload is the physical page id in the engine's KV pools.
The trie holds each page alive with a `PageBlockAllocator.pin()`
refcount, so prompt pages survive the request that prefilled them and a
later request whose prompt extends a cached prefix admits with those
pages shared (`allocator.adopt`) and only the tail prefilled.

Exactness discipline (why sharing is safe):

  - causal attention + absolute position embeddings mean a page's KV
    rows depend only on the token prefix up to and through that page —
    the trie path IS that prefix, so a path match is an exact KV match;
  - only FULL pages are cached, so an adopter's first write lands on a
    page boundary (a fresh page) — trie pages are never written after
    insertion and need no COW;
  - the match is capped at `(len(prompt) - 1) // page_size` pages: the
    last prompt token is always recomputed so the engine still produces
    first-token logits.

Eviction is LRU over leaves whose page refcount equals its pin count
(i.e. no live sequence shares it): under pool pressure the engine calls
`evict()` to return cold pages to the free list, cascading to parents
as leaves disappear. All trie state is guarded by one lock so a future
multi-threaded scheduler stays PT006-clean.
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional, Tuple

from .. import observability as _obs
from .block_allocator import PageBlockAllocator

__all__ = ["PrefixCache", "PrefixMatch"]

_HITS = _obs.registry().counter(
    "serving.prefix_cache.hits",
    "admissions whose prompt matched >= 1 cached page")
_MISSES = _obs.registry().counter(
    "serving.prefix_cache.misses",
    "admissions with no cached prefix page")
_EVICTED = _obs.registry().counter(
    "serving.prefix_cache.evicted_pages",
    "trie pages evicted under pool pressure")
_SHARED = _obs.registry().counter(
    "serving.prefix_cache.shared_tokens",
    "prompt tokens whose prefill was skipped via the prefix cache")
_PAGES = _obs.registry().gauge(
    "serving.prefix_cache.pages", "pages currently pinned by the trie")
# per-replica families (ROADMAP item 2): the fleet router's locality
# score is computed from the SAME counters operators see — a replica's
# trie labels its hit/pin/eviction traffic with its name
_R_HIT_TOK = _obs.registry().counter(
    "serving.prefix_cache.replica_hit_tokens",
    "prompt tokens matched in the trie at lookup, by replica",
    labels=("replica",))
_R_PINNED = _obs.registry().gauge(
    "serving.prefix_cache.replica_pinned_pages",
    "pages currently pinned by the replica's trie", labels=("replica",))
_R_EVICTED = _obs.registry().counter(
    "serving.prefix_cache.replica_evicted_pages",
    "trie pages evicted under pool pressure, by replica",
    labels=("replica",))


class _Node:
    __slots__ = ("key", "page", "parent", "children", "tick")

    def __init__(self, key: Optional[Tuple[int, ...]], page: Optional[int],
                 parent: Optional["_Node"]):
        self.key = key              # page_size-token tuple (None at root)
        self.page = page            # physical page id (None at root)
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.tick = 0               # LRU clock value of last touch


class PrefixMatch:
    """Result of a `lookup`: the matched pages, pinned against eviction
    until `release()`. The engine adopts the pages (taking its own
    refcounts) and then ALWAYS releases the match — also on every
    refusal path, so no admission failure leaks a pin."""

    __slots__ = ("_cache", "pages", "tokens", "_released")

    def __init__(self, cache: "PrefixCache", pages: List[int], tokens: int):
        self._cache = cache
        self.pages = pages
        self.tokens = tokens
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._cache._release_pins(self.pages)


class PrefixCache:
    """Radix trie of pinned KV pages shared across requests/tenants."""

    def __init__(self, allocator: PageBlockAllocator,
                 replica: Optional[str] = None):
        self._alloc = allocator
        self._ps = allocator.page_size
        self._root = _Node(None, None, None)
        self._lock = threading.Lock()
        # deterministic LRU clock (no wall time: seeded traces replay)
        self._clock = itertools.count(1)
        self._pages = 0
        self._replica = replica

    def set_replica(self, name: str) -> None:
        """Adopt a replica name for the labeled metric families (the
        FleetRouter names engines it was handed anonymously)."""
        self._replica = name
        if _obs.enabled():
            _R_PINNED.labels(replica=name).set(self._pages)

    # ---------------------------------------------------------------- keys
    def _chunk(self, prompt, i: int) -> Tuple[int, ...]:
        ps = self._ps
        return tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])

    def _max_pages(self, prompt) -> int:
        # never match the LAST prompt token: the engine must recompute
        # it to produce the first output logits
        return max(0, (len(prompt) - 1) // self._ps)

    # -------------------------------------------------------------- lookup
    def lookup(self, prompt) -> PrefixMatch:
        """Longest cached prefix of `prompt`, capped one token short of
        the full prompt. Matched pages are pinned until `release()`."""
        pages: List[int] = []
        with self._lock:
            tick = next(self._clock)
            node = self._root
            for i in range(self._max_pages(prompt)):
                child = node.children.get(self._chunk(prompt, i))
                if child is None:
                    break
                child.tick = tick
                pages.append(child.page)
                node = child
            for pg in pages:
                self._alloc.pin(pg)
            if _obs.enabled():
                (_HITS if pages else _MISSES).inc()
                if pages and self._replica is not None:
                    _R_HIT_TOK.labels(replica=self._replica).inc(
                        len(pages) * self._ps)
        return PrefixMatch(self, pages, len(pages) * self._ps)

    def match_length(self, prompt) -> int:
        """Tokens a `lookup` would share, without pinning or touching
        LRU state (used by the preemption fit guard)."""
        n = 0
        with self._lock:
            node = self._root
            for i in range(self._max_pages(prompt)):
                node = node.children.get(self._chunk(prompt, i))
                if node is None:
                    break
                n += 1
        return n * self._ps

    def note_adopted(self, tokens: int) -> None:
        """The engine admitted a request on `tokens` cached tokens."""
        if _obs.enabled():
            _SHARED.inc(tokens)

    def _release_pins(self, pages: List[int]) -> None:
        with self._lock:
            for pg in pages:
                self._alloc.unpin(pg)

    # -------------------------------------------------------------- insert
    def insert(self, prompt, seq_pages: List[int]) -> int:
        """Cache the FULL prompt pages of a sequence that just finished
        prefill (`seq_pages` is its physical page list). Existing nodes
        are kept (first writer wins — its KV is exact by construction);
        new nodes pin their page. Returns pages newly inserted."""
        n_full = len(prompt) // self._ps
        added = 0
        with self._lock:
            tick = next(self._clock)
            node = self._root
            for i in range(n_full):
                key = self._chunk(prompt, i)
                child = node.children.get(key)
                if child is None:
                    pg = seq_pages[i]
                    self._alloc.pin(pg)
                    child = _Node(key, pg, node)
                    node.children[key] = child
                    self._pages += 1
                    added += 1
                child.tick = tick
                node = child
            if _obs.enabled():
                _PAGES.set(self._pages)
                if self._replica is not None:
                    _R_PINNED.labels(
                        replica=self._replica).set(self._pages)
        return added

    # ------------------------------------------------------------ eviction
    def _evictable_locked(self) -> List[_Node]:
        out, stack = [], [self._root]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n.parent is not None and not n.children \
                    and self._alloc.refcount(n.page) \
                    == self._alloc.pinned(n.page):
                out.append(n)
        return out

    def evictable_pages(self) -> int:
        """Trie leaves no live sequence shares (an upper bound on what
        `evict` could free right now; cascading can expose more)."""
        with self._lock:
            return len(self._evictable_locked())

    def evict(self, need_pages: int) -> int:
        """LRU-evict cold leaves until `need_pages` pages went back to
        the free list or nothing evictable remains. Returns pages
        actually freed. Leaves still pinned by an outstanding
        `PrefixMatch` count as evictable but are the warmest (the
        lookup just touched them), so LRU takes them last — and their
        match pin keeps the page alive for the adopter regardless."""
        freed = 0
        with self._lock:
            while freed < need_pages:
                leaves = self._evictable_locked()
                if not leaves:
                    break
                victim = min(leaves, key=lambda n: n.tick)
                del victim.parent.children[victim.key]
                self._pages -= 1
                if self._alloc.unpin(victim.page):
                    freed += 1
                if _obs.enabled():
                    _EVICTED.inc()
                    if self._replica is not None:
                        _R_EVICTED.labels(replica=self._replica).inc()
            if _obs.enabled():
                _PAGES.set(self._pages)
                if self._replica is not None:
                    _R_PINNED.labels(
                        replica=self._replica).set(self._pages)
        return freed

    def flush(self) -> int:
        """Evict everything evictable (tests / engine shutdown)."""
        return self.evict(1 << 30)

    # --------------------------------------------------------------- stats
    @property
    def pages(self) -> int:
        with self._lock:
            return self._pages

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"pages": self._pages,
                    "evictable": len(self._evictable_locked())}
