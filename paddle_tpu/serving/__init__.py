"""Continuous-batching serving subsystem.

Modules over the Pallas paged-decode kernel
(`ops/pallas_paged.py` via `ops.paged_attention`):

  - `block_allocator`: fixed pool of page_size-token KV blocks with
    refcounts, per-sequence page tables, copy-on-write prefix sharing,
    trie pins, and utilization/fragmentation gauges;
  - `prefix_cache`: global radix trie of pinned prompt pages — a new
    request whose prompt extends a cached prefix admits with those
    pages shared and only the tail prefilled (LRU eviction under pool
    pressure);
  - `scheduler`: in-flight request scheduler — FCFS within a priority
    class, per-tenant token budgets, page-intact preemption, admission
    backpressure (`inference.Config.set_admission`) and per-request
    deadlines (`set_deadline` → falsy TimeoutResult partials);
  - `spec_decode`: n-gram self-drafting speculative decoding, verified
    in the engine's single ragged launch per step;
  - `engine`: `ServingEngine.add_request/step/collect`, a fixed-shape
    jitted decode step (one compile per model/slot-count) plus chunked
    prefill, for the llama/moe, gpt and mla families — each engine runs
    as a `prefill`, `decode`, or `colocated` (default) replica;
  - `handoff`: `KVPageHandoff`, the pin → export → import → unpin
    KV-page transfer between a prefill replica and a decode replica
    (bit-identical resume, no re-prefill);
  - `router`: `FleetRouter` spreading requests over N replicas by
    radix-trie prefix overlap vs queue depth (scaled by per-replica
    placement weights), brokering handoffs, and draining/re-admitting
    replicas on `CollectiveTimeout` faults;
  - `controller`: the SLO autopilot — `SLOTargets` plus the
    `EngineController` / `FleetController` feedback loops that actuate
    chunk size, spec-decode k, prefix-cache admission, graduated load
    shedding, placement weights and replica roles against declared
    targets (see docs/SERVING.md "Autopilot").

See docs/SERVING.md ("Continuous batching", "Disaggregated serving")
for sizing and usage.
"""

from typing import Any, Dict

from .. import observability as _obs
from ..observability import tracing as _tracing
from .block_allocator import PageBlockAllocator
from .controller import EngineController, FleetController, SLOTargets
from .engine import ServingEngine
from .handoff import KVPageHandoff
from .prefix_cache import PrefixCache
from .router import FleetRouter
from .scheduler import Request, Scheduler

__all__ = ["ServingEngine", "Request", "Scheduler", "PageBlockAllocator",
           "PrefixCache", "KVPageHandoff", "FleetRouter", "SLOTargets",
           "EngineController", "FleetController", "metrics", "slo"]


def metrics() -> Dict[str, Any]:
    """The serving.* slice of the registry snapshot (engine, prefix
    cache, and speculative-decode metric families)."""
    return {k: v for k, v in _obs.registry().snapshot().items()
            if k.startswith("serving.")}


def slo(qs=(50, 90, 99)) -> Dict[str, Any]:
    """Percentile summary of the per-request SLO histograms the tracing
    layer derives at each terminal event:
    {"serving.engine.ttft_seconds": {count, mean, p50, p90, p99}, ...}
    for queue-wait / TTFT / TPOT / e2e. Histograms with no finished
    requests yet report count 0 with None quantiles."""
    return _tracing.slo_summary(qs=qs)
