"""AMP (ref: python/paddle/amp/ — auto_cast O1/O2 list-based casting,
GradScaler dynamic loss scaling, decorate).

TPU-native notes: bf16 is the native mixed-precision dtype (no scaler needed —
bf16 has f32's exponent range); fp16 + GradScaler is kept for API parity. The
cast hook plugs into core.dispatch so every op application sees it, mirroring
the reference's AmpOperators black/white lists in the generated ad_funcs
(paddle/fluid/imperative/amp_auto_cast.cc).
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.dtypes import convert_dtype
from ..core.tensor import Tensor

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate", "decorate_tree",
           "WHITE_LIST", "BLACK_LIST"]

# ops that benefit from low precision (MXU ops)
WHITE_LIST = {
    "matmul", "linear", "conv1d", "conv2d", "conv3d", "einsum", "mm",
    "bmm", "sdpa", "flash_attention", "addmm",
}
# numerically sensitive ops stay f32
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "logsumexp", "softmax",
    "log_softmax", "cross_entropy", "bce", "bce_logits", "nll_loss",
    "kl_div", "ctc_loss", "cumsum", "norm", "layer_norm", "batch_norm",
    "rms_norm", "group_norm", "mean", "sum", "softmax_with_cross_entropy",
    "erfinv", "pow", "square",
}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white: set = set()
        self.custom_black: set = set()


_state = _AmpState()


def _is_float(a) -> bool:
    return np.issubdtype(a.dtype, np.floating) or a.dtype == jnp.bfloat16


def _cast_hook(op_name: str, arrays: Sequence):
    if not _state.enabled:
        return arrays
    white = (WHITE_LIST | _state.custom_white) - _state.custom_black
    black = (BLACK_LIST | _state.custom_black) - _state.custom_white
    if _state.level == "O2":
        if op_name in black:
            return [a.astype(jnp.float32) if _is_float(a) else a
                    for a in arrays]
        return [a.astype(_state.dtype) if _is_float(a) else a for a in arrays]
    # O1
    if op_name in white:
        return [a.astype(_state.dtype) if _is_float(a) else a for a in arrays]
    if op_name in black:
        return [a.astype(jnp.float32) if _is_float(a) else a for a in arrays]
    # promote to the widest float dtype present (paddle: keep-dtype ops)
    floats = [a.dtype for a in arrays if _is_float(a)]
    if floats and any(d == jnp.float32 for d in floats):
        return [a.astype(jnp.float32) if _is_float(a) else a for a in arrays]
    return arrays


class auto_cast:
    """with paddle.amp.auto_cast(level='O1', dtype='bfloat16'): ..."""

    def __init__(self, enable: bool = True, custom_white_list=None,
                 custom_black_list=None, level: str = "O1",
                 dtype: str = "bfloat16", use_promote: bool = True):
        self.enable = enable
        self.level = level
        self.dtype = convert_dtype(dtype)
        self.white = set(custom_white_list or ())
        self.black = set(custom_black_list or ())

    def __enter__(self):
        self._saved = (_state.enabled, _state.dtype, _state.level,
                       _state.custom_white, _state.custom_black)
        _state.enabled = self.enable
        _state.dtype = self.dtype
        _state.level = self.level
        _state.custom_white = self.white
        _state.custom_black = self.black
        dispatch.set_amp_cast_hook(_cast_hook if self.enable else None)
        return self

    def __exit__(self, *exc):
        (_state.enabled, _state.dtype, _state.level,
         _state.custom_white, _state.custom_black) = self._saved
        dispatch.set_amp_cast_hook(_cast_hook if _state.enabled else None)
        return False


amp_guard = auto_cast


def decorate(models, optimizers=None, level: str = "O2", dtype: str = "bfloat16",
             master_weight=None, save_dtype=None):
    """Cast model params to the AMP dtype (O2), enabling optimizer master
    weights (ref: paddle.amp.decorate)."""
    dt = convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dt)
    out_models = model_list[0] if single else model_list
    if optimizers is not None:
        opt_single = not isinstance(optimizers, (list, tuple))
        opt_list = [optimizers] if opt_single else list(optimizers)
        for o in opt_list:
            if master_weight is not False:
                o._multi_precision = True
        return out_models, (opt_list[0] if opt_single else opt_list)
    return out_models


def decorate_tree(tree, dtype="bfloat16"):
    """Functional O2 decorate for jitted SPMD steps: cast every floating
    leaf of a raw param pytree to the compute dtype, leaving integer leaves
    (and the f32 master copy, kept by the optimizer) untouched. This is the
    same O2 contract as `decorate` expressed as a pure tree transform."""
    import jax
    dt = convert_dtype(dtype) if isinstance(dtype, str) else dtype
    return jax.tree.map(
        lambda v: v.astype(dt) if _is_float(v) else v, tree)


class GradScaler:
    """Dynamic loss scaling (ref: python/paddle/amp/grad_scaler.py).

    On bf16 this is a near-no-op passthrough (use_dynamic_loss_scaling=False);
    kept for fp16 parity: scale → backward → unscale+check-finite → step/skip.
    """

    def __init__(self, enable: bool = True, init_loss_scaling: float = 65536.0,
                 incr_ratio: float = 2.0, decr_ratio: float = 0.5,
                 incr_every_n_steps: int = 2000,
                 decr_every_n_nan_or_inf: int = 1,
                 use_dynamic_loss_scaling: bool = True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False  # guards the unscale→clip→step pattern

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer) -> None:
        if not self._enable or self._unscaled:
            return
        self._unscaled = True
        found = False
        inv = 1.0 / self._scale
        for p in optimizer._param_groups:
            if p.grad is None:
                continue
            g = p.grad._data.astype(jnp.float32) * inv
            if not bool(jnp.all(jnp.isfinite(g))):
                found = True
            p.grad._data = g.astype(p.grad._data.dtype)
        self._found_inf = found

    def step(self, optimizer) -> None:
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss) -> None:
        self.step(optimizer)

    def update(self) -> None:
        self._unscaled = False
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def is_enable(self) -> bool:
        return self._enable

    def get_loss_scaling(self) -> float:
        return self._scale

    def state_dict(self) -> dict:
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, state: dict) -> None:
        self._scale = state["scale"]
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)
