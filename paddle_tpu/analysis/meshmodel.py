"""Static model of mesh-axis environments and PartitionSpec flow
(docs/ANALYSIS.md, sharding-verification section).

Layered on :class:`PackageIndex` the way ``kernelmodel.py`` models
``pallas_call`` sites: for each ``shard_map`` site the model recovers —
through the same flow-insensitive local environment — the *axis
environment* (axis names, and literal sizes where the mesh construction
is literal), the ``in_specs``/``out_specs`` literals and their flow
through locals and ``sanitize_spec``, the resolved body function (with
``functools.partial`` bindings subtracted), and the outer invocation
arguments.  ``NamedSharding``/``with_sharding_constraint`` placements and
``vmap(axis_name=...)`` bindings get the same treatment, and every
collective axis-name argument (``psum``/``all_gather``/``ppermute``/...)
is extracted per function so rules can intersect it with the
environments of the shard_map sites that reach it.

Axis environments come from the constructions the distributed layer
actually uses: ``ProcessMesh(ids, dim_names=[...])`` (sizes from a
literal id array), ``build_hybrid_mesh(*_degree=...)`` (the fixed 8-axis
hybrid order, sizes from literal degree kwargs, absent degrees = 1),
``Mesh(devs, ("a", "b"))`` (including names routed through module
constants like ``AXIS_ORDER`` and partially-symbolic tuples), and a
``shard_map`` ``axis_names=`` literal.  A mesh that resolves to
``get_mesh()`` / ``_mesh_of(...)`` is *ambient* — configurable at
runtime, axes unknown.  Everything else degrades to "unknown" rather
than guessing, so an unresolvable mesh or spec opts its site out of the
checks that need the missing piece — the same discipline as the kernel
model.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import (FunctionInfo, ModuleInfo, PackageIndex, _last_name,
                        partial_inner, walk_shallow)
from .kernelmodel import Env, _int_const, _kw, _lookup_def, unparse

#: the fixed axis order ``build_hybrid_mesh`` constructs (mesh.py) —
#: the dcn_* axes are the multi-slice DCN tier (outermost in the mesh)
HYBRID_AXES = ("dcn_pp", "dcn_dp", "pp", "dp", "sharding", "sep", "ep",
               "mp")

#: call names that return the ambient / runtime-configured mesh
AMBIENT_MESH_FUNCS = {"get_mesh", "_mesh_of", "current_mesh"}

#: spec constructors; bare ``P`` counts only when imported as PartitionSpec
SPEC_CTORS = {"PartitionSpec"}

#: collectives that take an axis-name argument (name -> positional index)
COLLECTIVE_AXIS_ARG = {"psum": 1, "pmax": 1, "pmin": 1, "pmean": 1,
                       "all_gather": 1, "psum_scatter": 1, "all_to_all": 1,
                       "ppermute": 1, "pshuffle": 1, "pbroadcast": 1,
                       "axis_index": 0}

#: sentinel entry for a spec element the model cannot resolve
SYMBOLIC = object()

#: array constructors whose first literal tuple argument is the shape
_SHAPE_CTORS = {"zeros", "ones", "empty", "full", "normal", "uniform"}


class OrderedEnv(Env):
    """:class:`Env` whose intra-scope record order is *source order*, so
    the last assignment to a name in each scope wins. Spec flow needs
    this: the reassignment idiom ``spec = sanitize_spec(mesh, spec)``
    must resolve ``spec`` to the sanitized value, not whichever binding
    the walk happened to visit last."""

    def __init__(self, mi: ModuleInfo, fi: Optional[FunctionInfo]):
        super().__init__(mi, fi)
        if fi is None:
            return
        parts = fi.qualname.split(".")
        for i in range(1, len(parts) + 1):
            anc = mi.functions.get(".".join(parts[:i]))
            if anc is not None and not isinstance(anc.node, ast.Lambda):
                assigns = sorted(
                    (n for n in walk_shallow(anc.node)
                     if isinstance(n, (ast.Assign, ast.AnnAssign))),
                    key=lambda n: (n.lineno, n.col_offset))
                for node in assigns:
                    self._record(node)


# ---------------------------------------------------------------------------
# literal resolution helpers
# ---------------------------------------------------------------------------

def _module_const(index: PackageIndex, mi: ModuleInfo,
                  name: str) -> Optional[ast.AST]:
    """Top-level binding of ``name`` in ``mi``, following one ``from x
    import name`` hop so constants like ``PP_AXIS``/``AXIS_ORDER`` resolve
    across modules."""
    for node in mi.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name:
            return node.value
    if name in mi.import_names:
        src, orig = mi.import_names[name]
        smi = index.modules.get(src)
        if smi is not None and smi is not mi:
            return _module_const(index, smi, orig)
    return None


def _resolve(index: PackageIndex, mi: ModuleInfo, env: Env,
             node: Optional[ast.AST]) -> Optional[ast.AST]:
    """Env.resolve plus one cross-module constant hop."""
    node = env.resolve(node)
    if isinstance(node, ast.Name):
        const = _module_const(index, mi, node.id)
        if const is not None:
            return env.resolve(const)
    return node


def _str_const(index: PackageIndex, mi: ModuleInfo, env: Env,
               node: Optional[ast.AST]) -> Optional[str]:
    node = _resolve(index, mi, env, node)
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _axis_names(index: PackageIndex, mi: ModuleInfo, env: Env,
                node: Optional[ast.AST]) -> Optional[Tuple[List[str], bool]]:
    """Literal axis names from a tuple/list/set/frozenset expression —
    ``(names, complete)`` where ``complete`` is False when some element
    was symbolic (a partially-symbolic axis tuple)."""
    node = _resolve(index, mi, env, node)
    if isinstance(node, ast.Call) and _last_name(node.func) in ("frozenset",
                                                                "set",
                                                                "tuple"):
        if len(node.args) == 1:
            node = _resolve(index, mi, env, node.args[0])
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value], True
    if not isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return None
    names: List[str] = []
    complete = True
    for e in node.elts:
        s = _str_const(index, mi, env, e)
        if s is None:
            complete = False
        else:
            names.append(s)
    return names, complete


# ---------------------------------------------------------------------------
# axis environments
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class AxisEnv:
    """Axis names visible to code under one mesh/shard_map construction.
    ``complete`` means ``axes`` is the *whole* set — only then may a rule
    claim an axis name is absent. ``sizes`` holds literal sizes (None =
    unknown)."""
    axes: Tuple[str, ...]
    sizes: Dict[str, Optional[int]]
    complete: bool
    source: str                        # "ProcessMesh"/"build_hybrid_mesh"/...
    ambient: bool = False              # get_mesh()/_mesh_of(): configurable

    def size(self, name: str) -> Optional[int]:
        return self.sizes.get(name)


def _literal_shape(node: ast.AST) -> Optional[List[int]]:
    """Shape of a literal nested list/tuple (the ProcessMesh id array)."""
    if not isinstance(node, (ast.List, ast.Tuple)):
        return None
    dims = [len(node.elts)]
    if node.elts and isinstance(node.elts[0], (ast.List, ast.Tuple)):
        inner = _literal_shape(node.elts[0])
        if inner is None:
            return None
        dims.extend(inner)
    return dims


def mesh_env(index: PackageIndex, mi: ModuleInfo, env: Env,
             expr: Optional[ast.AST],
             _depth: int = 0) -> Optional[AxisEnv]:
    """Axis environment of a mesh-valued expression, or None when
    unresolvable. ``ambient=True`` marks a mesh fetched from runtime
    configuration (``get_mesh()``/``_mesh_of(...)``) — axes unknown but
    *known to be configurable* (PS306's trigger)."""
    if _depth > 4:
        return None
    expr = _resolve(index, mi, env, expr)
    if expr is None:
        return None
    # m.jax_mesh where m is a ProcessMesh(...) construction
    if isinstance(expr, ast.Attribute) and expr.attr == "jax_mesh":
        return mesh_env(index, mi, env, expr.value, _depth + 1)
    if not isinstance(expr, ast.Call):
        return None
    name = _last_name(expr.func)
    if name in AMBIENT_MESH_FUNCS:
        return AxisEnv(axes=(), sizes={}, complete=False, source=name,
                       ambient=True)
    if name == "mesh_context":
        return mesh_env(index, mi, env, expr.args[0] if expr.args else None,
                        _depth + 1)
    if name == "ProcessMesh":
        ids = _resolve(index, mi, env, expr.args[0] if expr.args else None)
        dim_names = (expr.args[1] if len(expr.args) > 1
                     else _kw(expr, "dim_names"))
        shape = _literal_shape(ids) if ids is not None else None
        got = _axis_names(index, mi, env, dim_names) \
            if dim_names is not None else None
        if got is None:
            if shape is None:
                return None
            axes = tuple(f"d{i}" for i in range(len(shape)))
            complete = True
        else:
            axes, complete = tuple(got[0]), got[1]
        sizes: Dict[str, Optional[int]] = {a: None for a in axes}
        if shape is not None and complete and len(shape) == len(axes):
            sizes = dict(zip(axes, shape))
        return AxisEnv(axes=axes, sizes=sizes, complete=complete,
                       source="ProcessMesh")
    if name == "build_hybrid_mesh":
        sizes = {a: 1 for a in HYBRID_AXES}
        complete = True
        for kw in expr.keywords:
            if kw.arg is None:
                complete = False          # **kwargs: degrees unknown
                continue
            if kw.arg.endswith("_degree"):
                axis = kw.arg[: -len("_degree")]
                if axis in sizes:
                    sizes[axis] = _int_const(
                        _resolve(index, mi, env, kw.value))
        if expr.args:
            # positional signature: dp, mp, pp, sharding, sep, ep,
            # dcn_dp, dcn_pp
            order = ("dp", "mp", "pp", "sharding", "sep", "ep",
                     "dcn_dp", "dcn_pp")
            for i, arg in enumerate(expr.args[: len(order)]):
                sizes[order[i]] = _int_const(_resolve(index, mi, env, arg))
        return AxisEnv(axes=HYBRID_AXES, sizes=sizes, complete=complete,
                       source="build_hybrid_mesh")
    if name == "Mesh":
        names_expr = (expr.args[1] if len(expr.args) > 1
                      else _kw(expr, "axis_names"))
        got = _axis_names(index, mi, env, names_expr) \
            if names_expr is not None else None
        if got is None:
            return None
        axes, complete = got
        return AxisEnv(axes=tuple(axes), sizes={a: None for a in axes},
                       complete=complete, source="Mesh")
    return None


# ---------------------------------------------------------------------------
# PartitionSpec flow
# ---------------------------------------------------------------------------

def _is_spec_ctor(mi: ModuleInfo, func: ast.AST) -> bool:
    name = _last_name(func)
    if name == "PartitionSpec":
        return True
    if name is None:
        return False
    imp = mi.import_names.get(name)
    return imp is not None and imp[1] == "PartitionSpec"


@dataclasses.dataclass
class SpecModel:
    """One PartitionSpec value as the model understands it. ``entries``
    is None when the rank is unknown (``P(*...)`` star-args or a
    non-literal); each entry is None, a str axis name, a tuple of axis
    names, or :data:`SYMBOLIC`."""
    node: ast.AST
    entries: Optional[List[object]] = None
    axes: Set[str] = dataclasses.field(default_factory=set)
    symbolic: bool = False             # some element unresolved
    sanitized: bool = False            # flowed through sanitize_spec
    layer_declared: bool = False       # came from a `_sharding_spec` slot
    resolved: bool = True              # False: value is not a spec we know

    @property
    def min_rank(self) -> Optional[int]:
        """Entries after stripping trailing Nones — the smallest array
        rank this spec legally applies to."""
        if self.entries is None or self.symbolic:
            return None
        n = len(self.entries)
        while n and self.entries[n - 1] is None:
            n -= 1
        return n

    def entry_axes(self, i: int) -> Tuple[str, ...]:
        if self.entries is None or i >= len(self.entries):
            return ()
        e = self.entries[i]
        if isinstance(e, str):
            return (e,)
        if isinstance(e, tuple):
            return e
        return ()

    def text(self) -> str:
        return unparse(self.node)


def build_spec(index: PackageIndex, mi: ModuleInfo, env: Env,
               expr: Optional[ast.AST],
               _depth: int = 0) -> Optional[SpecModel]:
    """SpecModel of a spec-valued expression, or None when it resolves to
    nothing spec-like (an unknown call, a subscript, a parameter...)."""
    if _depth > 4 or expr is None:
        return None
    expr = env.resolve(expr)
    if isinstance(expr, ast.BoolOp) and isinstance(expr.op, ast.Or):
        parts = [build_spec(index, mi, env, v, _depth + 1)
                 for v in expr.values]
        parts = [p for p in parts if p is not None]
        if not parts:
            return None
        merged = SpecModel(node=expr, entries=None, symbolic=True)
        for p in parts:
            merged.axes |= p.axes
            merged.layer_declared |= p.layer_declared
            merged.sanitized |= p.sanitized
        return merged
    if isinstance(expr, ast.Attribute) and expr.attr == "_sharding_spec":
        return SpecModel(node=expr, entries=None, symbolic=True,
                         layer_declared=True)
    if not isinstance(expr, ast.Call):
        return None
    name = _last_name(expr.func)
    if name == "getattr" and len(expr.args) >= 2:
        attr = expr.args[1]
        if isinstance(attr, ast.Constant) and attr.value == "_sharding_spec":
            return SpecModel(node=expr, entries=None, symbolic=True,
                             layer_declared=True)
        return None
    if name == "sanitize_spec":
        inner = build_spec(index, mi, env,
                           expr.args[1] if len(expr.args) > 1
                           else _kw(expr, "spec"), _depth + 1)
        if inner is None:
            inner = SpecModel(node=expr, entries=None, symbolic=True)
        inner.sanitized = True
        return inner
    if not _is_spec_ctor(mi, expr.func):
        return None
    spec = SpecModel(node=expr, entries=[])
    for a in expr.args:
        if isinstance(a, ast.Starred):
            spec.entries = None
            spec.symbolic = True
            continue
        a = _resolve(index, mi, env, a)
        entry: object = SYMBOLIC
        if isinstance(a, ast.Constant) and a.value is None:
            entry = None
        elif isinstance(a, ast.Constant) and isinstance(a.value, str):
            entry = a.value
            spec.axes.add(a.value)
        elif isinstance(a, (ast.Tuple, ast.List)):
            names = []
            ok = True
            for e in a.elts:
                s = _str_const(index, mi, env, e)
                if s is None:
                    ok = False
                else:
                    names.append(s)
                    spec.axes.add(s)
            entry = tuple(names) if ok else SYMBOLIC
        else:
            s = _str_const(index, mi, env, a)
            if s is not None:
                entry = s
                spec.axes.add(s)
        if entry is SYMBOLIC:
            spec.symbolic = True
        if spec.entries is not None:
            spec.entries.append(entry)
    return spec


def _spec_seq(index: PackageIndex, mi: ModuleInfo, env: Env,
              expr: Optional[ast.AST]
              ) -> Tuple[Optional[List[SpecModel]], bool]:
    """``(specs, is_sequence)`` for an in_specs/out_specs expression.
    A literal tuple/list yields one SpecModel per element (unresolvable
    elements become ``resolved=False`` placeholders); dict-valued specs
    (pytree tables, e.g. pp_exec's param_specs) yield their values with
    ``is_sequence=False`` since the tree structure is not positional."""
    expr = env.resolve(expr)
    if expr is None:
        return None, False
    if isinstance(expr, (ast.Tuple, ast.List)):
        out = []
        for e in expr.elts:
            if isinstance(e, ast.Starred):
                return None, False
            s = build_spec(index, mi, env, e)
            out.append(s if s is not None
                       else SpecModel(node=e, entries=None, symbolic=True,
                                      resolved=False))
        return out, True
    if isinstance(expr, ast.Dict):
        out = []
        for v in expr.values:
            s = build_spec(index, mi, env, v)
            if s is not None:
                out.append(s)
        return (out or None), False
    one = build_spec(index, mi, env, expr)
    return ([one], False) if one is not None else (None, False)


# ---------------------------------------------------------------------------
# sites
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ShardMapSite:
    mi: ModuleInfo
    fi: Optional[FunctionInfo]
    call: ast.Call
    env: Optional[AxisEnv] = None
    manual_axes: Optional[Tuple[str, ...]] = None   # axis_names= literal
    in_specs: Optional[List[SpecModel]] = None
    in_specs_seq: bool = False
    out_specs: Optional[List[SpecModel]] = None
    out_specs_seq: bool = False
    body_keys: Set[str] = dataclasses.field(default_factory=set)
    body_fi: Optional[FunctionInfo] = None
    body_bound_kw: Set[str] = dataclasses.field(default_factory=set)
    body_bound_pos: int = 0
    arg_exprs: Optional[List[ast.AST]] = None       # outer (...)(*args)

    @property
    def line(self) -> int:
        return self.call.lineno

    @property
    def qualname(self) -> str:
        return self.fi.qualname if self.fi is not None else "<module>"

    def bound_axes(self) -> Optional[Tuple[str, ...]]:
        """Axis names this site binds for its body, or None when the
        environment is unknown/incomplete. ``axis_names=`` narrows a
        known mesh; alone it is exact only for the named subset."""
        if self.env is not None and self.env.complete:
            if self.manual_axes is not None:
                return tuple(a for a in self.env.axes
                             if a in set(self.manual_axes))
            return self.env.axes
        if self.manual_axes is not None:
            return self.manual_axes
        return None

    def body_positional(self) -> Optional[int]:
        """Positional-parameter count of the resolved body after
        subtracting partial bindings (None: unresolved or *args)."""
        if self.body_fi is None:
            return None
        a = self.body_fi.node.args
        if a.vararg is not None:
            return None
        params = [p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
        params = params[self.body_bound_pos:]
        return len([p for p in params if p not in self.body_bound_kw])


@dataclasses.dataclass
class ShardingSite:
    """A ``NamedSharding(mesh, spec)`` (or pjit ``in_shardings=``)
    placement: where PS303/PS304/PS306 look."""
    mi: ModuleInfo
    fi: Optional[FunctionInfo]
    call: ast.Call
    env: Optional[AxisEnv] = None
    spec: Optional[SpecModel] = None
    placed_expr: Optional[ast.AST] = None   # device_put(arr, NS(...)) arr

    @property
    def line(self) -> int:
        return self.call.lineno

    @property
    def qualname(self) -> str:
        return self.fi.qualname if self.fi is not None else "<module>"


@dataclasses.dataclass
class VmapSite:
    mi: ModuleInfo
    fi: Optional[FunctionInfo]
    call: ast.Call
    axis_name: str
    body_keys: Set[str] = dataclasses.field(default_factory=set)

    @property
    def qualname(self) -> str:
        return self.fi.qualname if self.fi is not None else "<module>"


@dataclasses.dataclass
class CollectiveUse:
    mi: ModuleInfo
    fi: FunctionInfo
    call: ast.Call
    name: str
    axes: Optional[List[str]] = None   # literal axis names, None = symbolic


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------

def literal_rank(index: PackageIndex, mi: ModuleInfo, env: Env,
                 expr: Optional[ast.AST]) -> Optional[int]:
    """Rank of an array expression when statically evident: a literal
    shape constructor (``jnp.zeros((4, 8))``-style) or a
    ``ShapeDtypeStruct((..., ...), ...)``."""
    expr = env.resolve(expr)
    if not isinstance(expr, ast.Call):
        return None
    name = _last_name(expr.func)
    if name in _SHAPE_CTORS or name == "ShapeDtypeStruct":
        shape = expr.args[0] if expr.args else _kw(expr, "shape")
        shape = _resolve(index, mi, env, shape)
        if isinstance(shape, (ast.Tuple, ast.List)):
            return len(shape.elts)
    return None


def literal_shape(index: PackageIndex, mi: ModuleInfo, env: Env,
                  expr: Optional[ast.AST]) -> Optional[List[Optional[int]]]:
    """Per-dim literal sizes of an array expression (None entries for
    non-literal dims), or None when the shape is not statically evident."""
    expr = env.resolve(expr)
    if not isinstance(expr, ast.Call):
        return None
    name = _last_name(expr.func)
    if name in _SHAPE_CTORS or name == "ShapeDtypeStruct":
        shape = expr.args[0] if expr.args else _kw(expr, "shape")
        shape = _resolve(index, mi, env, shape)
        if isinstance(shape, (ast.Tuple, ast.List)):
            return [_int_const(_resolve(index, mi, env, e))
                    for e in shape.elts]
    return None


def _resolve_body(site: ShardMapSite, index: PackageIndex,
                  env: Env, expr: Optional[ast.AST]) -> None:
    expr = env.resolve(expr)
    if expr is None:
        return
    inner = partial_inner(expr)
    while inner is not None:
        site.body_bound_kw |= {kw.arg for kw in expr.keywords if kw.arg}
        site.body_bound_pos += len(expr.args) - 1
        expr = env.resolve(inner)
        inner = partial_inner(expr) if expr is not None else None
    if isinstance(expr, ast.Name):
        target = _lookup_def(site.mi, site.fi, expr.id)
        if target is not None:
            site.body_fi = target
    elif isinstance(expr, ast.Lambda):
        for fi in site.mi.functions.values():
            if fi.node is expr:
                site.body_fi = fi
                break


class MeshModel:
    """All shard_map / NamedSharding / vmap(axis_name=) sites, spec
    literals and collective uses in one indexed package."""

    def __init__(self, index: PackageIndex):
        self.index = index
        self.shard_map_sites: List[ShardMapSite] = []
        self.sharding_sites: List[ShardingSite] = []
        self.vmap_sites: List[VmapSite] = []
        #: (mi, qualname, SpecModel) for every spec literal in the package
        self.spec_literals: List[Tuple[ModuleInfo, str, SpecModel]] = []
        #: function key -> collective uses lexically inside it
        self.collectives: Dict[str, List[CollectiveUse]] = {}
        self._build()

    # -- construction ----------------------------------------------------

    def _build(self) -> None:
        index = self.index
        for mi in index.modules.values():
            outer_of: Dict[int, ast.Call] = {}
            for node in ast.walk(mi.tree):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Call):
                    outer_of[id(node.func)] = node
            seen: Set[int] = set()
            for fi_or_none, call in index._all_calls(mi):
                if id(call) in seen:
                    continue
                name = _last_name(call.func)
                if name == "shard_map":
                    seen.add(id(call))
                    self._parse_shard_map(mi, fi_or_none, call,
                                          outer_of.get(id(call)))
                elif name == "NamedSharding":
                    seen.add(id(call))
                    self._parse_sharding(mi, fi_or_none, call)
                elif name in ("vmap", "pmap"):
                    seen.add(id(call))
                    self._parse_vmap(mi, fi_or_none, call)
            for fi in mi.functions.values():
                self._collect_specs_and_collectives(mi, fi)
            self._collect_module_specs(mi)
            self._attach_placements(mi)
        self.shard_map_sites.sort(key=lambda s: (s.mi.rel, s.line))
        self.sharding_sites.sort(key=lambda s: (s.mi.rel, s.line))

    def _parse_shard_map(self, mi: ModuleInfo, fi: Optional[FunctionInfo],
                         call: ast.Call, outer: Optional[ast.Call]) -> None:
        env = OrderedEnv(mi, fi)
        site = ShardMapSite(mi=mi, fi=fi, call=call)
        mesh_expr = (call.args[1] if len(call.args) > 1
                     else _kw(call, "mesh"))
        in_expr = (call.args[2] if len(call.args) > 2
                   else _kw(call, "in_specs"))
        out_expr = (call.args[3] if len(call.args) > 3
                    else _kw(call, "out_specs"))
        site.env = mesh_env(self.index, mi, env, mesh_expr) \
            if mesh_expr is not None else None
        names_expr = _kw(call, "axis_names")
        if names_expr is not None:
            got = _axis_names(self.index, mi, env, names_expr)
            if got is not None and got[1]:
                site.manual_axes = tuple(got[0])
        if in_expr is not None:
            site.in_specs, site.in_specs_seq = _spec_seq(
                self.index, mi, env, in_expr)
        if out_expr is not None:
            site.out_specs, site.out_specs_seq = _spec_seq(
                self.index, mi, env, out_expr)
        body_expr = call.args[0] if call.args else _kw(call, "f")
        if body_expr is not None:
            site.body_keys = self.index._direct_func_keys(mi, fi, body_expr)
            _resolve_body(site, self.index, env, body_expr)
        if outer is not None and not any(isinstance(a, ast.Starred)
                                         for a in outer.args):
            site.arg_exprs = list(outer.args)
        self.shard_map_sites.append(site)

    def _parse_sharding(self, mi: ModuleInfo, fi: Optional[FunctionInfo],
                        call: ast.Call) -> None:
        env = OrderedEnv(mi, fi)
        site = ShardingSite(mi=mi, fi=fi, call=call)
        mesh_expr = call.args[0] if call.args else _kw(call, "mesh")
        spec_expr = (call.args[1] if len(call.args) > 1
                     else _kw(call, "spec"))
        site.env = mesh_env(self.index, mi, env, mesh_expr) \
            if mesh_expr is not None else None
        site.spec = build_spec(self.index, mi, env, spec_expr) \
            if spec_expr is not None else None
        self.sharding_sites.append(site)

    def _parse_vmap(self, mi: ModuleInfo, fi: Optional[FunctionInfo],
                    call: ast.Call) -> None:
        name_expr = _kw(call, "axis_name")
        if name_expr is None:
            return
        env = OrderedEnv(mi, fi)
        axis = _str_const(self.index, mi, env, name_expr)
        if axis is None:
            return
        keys = self.index._direct_func_keys(
            mi, fi, call.args[0]) if call.args else set()
        self.vmap_sites.append(VmapSite(mi=mi, fi=fi, call=call,
                                        axis_name=axis, body_keys=keys))

    def _collect_specs_and_collectives(self, mi: ModuleInfo,
                                       fi: FunctionInfo) -> None:
        env: Optional[Env] = None
        uses: List[CollectiveUse] = []
        for _, bare, call in fi.calls:
            if bare in COLLECTIVE_AXIS_ARG:
                if env is None:
                    env = OrderedEnv(mi, fi)
                idx = COLLECTIVE_AXIS_ARG[bare]
                axis_expr = (call.args[idx] if len(call.args) > idx
                             else (_kw(call, "axis_name")
                                   or _kw(call, "axis")))
                axes: Optional[List[str]] = None
                if axis_expr is not None:
                    got = _axis_names(self.index, mi, env, axis_expr)
                    if got is not None and got[1]:
                        axes = got[0]
                uses.append(CollectiveUse(mi=mi, fi=fi, call=call,
                                          name=bare, axes=axes))
            if isinstance(call, ast.Call) \
                    and _is_spec_ctor(mi, call.func):
                if env is None:
                    env = OrderedEnv(mi, fi)
                spec = build_spec(self.index, mi, env, call)
                if spec is not None:
                    self.spec_literals.append((mi, fi.qualname, spec))
        if uses:
            self.collectives[fi.key] = uses

    def _collect_module_specs(self, mi: ModuleInfo) -> None:
        env = OrderedEnv(mi, None)
        for node in walk_shallow(mi.tree):
            if isinstance(node, ast.Call) and _is_spec_ctor(mi, node.func):
                spec = build_spec(self.index, mi, env, node)
                if spec is not None:
                    self.spec_literals.append((mi, "<module>", spec))

    def _attach_placements(self, mi: ModuleInfo) -> None:
        """Pair each NamedSharding site with the array expression it
        places (``device_put(arr, NS)``/``with_sharding_constraint``)."""
        ns_by_id = {id(s.call): s for s in self.sharding_sites
                    if s.mi is mi}
        if not ns_by_id:
            return
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call) or len(node.args) < 2:
                continue
            name = _last_name(node.func)
            if name not in ("device_put", "global_device_put",
                            "with_sharding_constraint"):
                continue
            site = ns_by_id.get(id(node.args[1]))
            if site is not None:
                site.placed_expr = node.args[0]

    # -- queries ---------------------------------------------------------

    def region_of(self, body_keys: Set[str]) -> Set[str]:
        """Function keys reachable from a shard_map/vmap body closure."""
        return self.index.reachable_from(set(body_keys))

    def region_vmap_axes(self, region: Set[str]) -> Set[str]:
        """Axis names bound by vmap(axis_name=...) sites whose enclosing
        function lies in ``region`` — additionally legal for collectives
        under that region."""
        out: Set[str] = set()
        for v in self.vmap_sites:
            if (v.fi is not None and v.fi.key in region) \
                    or any(k in region for k in v.body_keys):
                out.add(v.axis_name)
        return out


def build_mesh_model(index: PackageIndex) -> MeshModel:
    return MeshModel(index)
