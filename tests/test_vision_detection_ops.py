"""paddle.vision.ops detection operators (ref: python/paddle/vision/ops.py
— roi_align/roi_pool/nms/deform_conv2d; SURVEY §2.2 vision row).

Oracles: numpy hand-rolled NMS/roi_align; torch conv2d for the
zero-offset deform_conv degenerate case (torchvision is not in the image).
"""

import numpy as np
import torch

import paddle_tpu as paddle
from paddle_tpu.vision import ops as V


def _np_nms(boxes, scores, thr):
    order = np.argsort(-scores)
    keep = []
    sup = np.zeros(len(boxes), bool)
    for i in order:
        if sup[i]:
            continue
        keep.append(i)
        for j in order:
            if j == i or sup[j]:
                continue
            xx1 = max(boxes[i, 0], boxes[j, 0])
            yy1 = max(boxes[i, 1], boxes[j, 1])
            xx2 = min(boxes[i, 2], boxes[j, 2])
            yy2 = min(boxes[i, 3], boxes[j, 3])
            inter = max(xx2 - xx1, 0) * max(yy2 - yy1, 0)
            a_i = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
            a_j = (boxes[j, 2] - boxes[j, 0]) * (boxes[j, 3] - boxes[j, 1])
            union = a_i + a_j - inter
            if union > 0 and inter / union > thr and scores[j] <= scores[i]:
                sup[j] = True
    return keep


class TestNMS:
    def test_vs_numpy_reference(self):
        rng = np.random.RandomState(0)
        xy = rng.rand(40, 2) * 60
        wh = rng.rand(40, 2) * 20 + 2
        boxes = np.concatenate([xy, xy + wh], 1).astype(np.float32)
        scores = rng.rand(40).astype(np.float32)
        for thr in (0.2, 0.5, 0.8):
            got = V.nms(paddle.to_tensor(boxes), thr,
                        scores=paddle.to_tensor(scores)).numpy()
            ref = _np_nms(boxes, scores, thr)
            np.testing.assert_array_equal(got, ref)

    def test_top_k_and_categories(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [30, 30, 40, 40]],
                         np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        keep = V.nms(paddle.to_tensor(boxes), 0.3,
                     scores=paddle.to_tensor(scores)).numpy()
        np.testing.assert_array_equal(keep, [0, 2])
        # different categories: overlapping boxes both survive
        cats = np.array([0, 1, 0], np.int64)
        keep2 = V.nms(paddle.to_tensor(boxes), 0.3,
                      scores=paddle.to_tensor(scores),
                      category_idxs=paddle.to_tensor(cats),
                      categories=[0, 1]).numpy()
        np.testing.assert_array_equal(keep2, [0, 1, 2])
        keep3 = V.nms(paddle.to_tensor(boxes), 0.3,
                      scores=paddle.to_tensor(scores), top_k=1).numpy()
        np.testing.assert_array_equal(keep3, [0])


class TestRoiAlign:
    def test_whole_image_box_equals_interpolation(self):
        """A box covering exactly the feature map, pooled to HxW with
        sampling at pixel centers, reproduces the map itself."""
        H = W = 6
        feat = np.arange(H * W, dtype=np.float32).reshape(1, 1, H, W)
        boxes = np.array([[0.0, 0.0, W, H]], np.float32)
        out = V.roi_align(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                          paddle.to_tensor(np.array([1], np.int32)),
                          output_size=(H, W), spatial_scale=1.0,
                          sampling_ratio=1, aligned=False)
        got = out.numpy()[0, 0]
        # sampling_ratio=1: one center sample per bin → bilinear at centers
        yy = np.arange(H) + 0.5
        xx = np.arange(W) + 0.5
        ref = np.empty((H, W), np.float32)
        for i, y in enumerate(yy):
            for j, x in enumerate(xx):
                y0, x0 = int(min(np.floor(y), H - 1)), int(min(np.floor(x),
                                                              W - 1))
                y1, x1 = min(y0 + 1, H - 1), min(x0 + 1, W - 1)
                wy, wx = y - y0, x - x0
                f = feat[0, 0]
                ref[i, j] = (f[y0, x0] * (1 - wy) * (1 - wx)
                             + f[y0, x1] * (1 - wy) * wx
                             + f[y1, x0] * wy * (1 - wx)
                             + f[y1, x1] * wy * wx)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    def test_out_of_bounds_samples_are_zeroed(self):
        """Samples beyond the [-1, size] band contribute 0, not the
        edge-clamped value (reference border semantics)."""
        H = W = 4
        feat = np.full((1, 1, H, W), 5.0, np.float32)
        # box far outside the map: every sample lands past W+? → all-zero
        boxes = np.array([[20.0, 20.0, 30.0, 30.0]], np.float32)
        out = V.roi_align(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                          paddle.to_tensor(np.array([1], np.int32)),
                          output_size=2, spatial_scale=1.0,
                          sampling_ratio=1, aligned=False)
        np.testing.assert_allclose(out.numpy(), 0.0, atol=1e-6)
        # box hanging half off the right edge: the outside half pools 0,
        # so means must be strictly below the constant value
        boxes2 = np.array([[2.0, 0.0, 10.0, 4.0]], np.float32)
        out2 = V.roi_align(paddle.to_tensor(feat), paddle.to_tensor(boxes2),
                           paddle.to_tensor(np.array([1], np.int32)),
                           output_size=(1, 2), spatial_scale=1.0,
                           sampling_ratio=2, aligned=False)
        o = out2.numpy()[0, 0, 0]
        assert o[0] > 0.0 and o[1] < 5.0

    def test_shapes_and_batching(self):
        rng = np.random.RandomState(1)
        feat = rng.randn(2, 3, 16, 16).astype(np.float32)
        boxes = np.array([[0, 0, 8, 8], [4, 4, 12, 12], [0, 0, 16, 16]],
                         np.float32)
        bn = np.array([2, 1], np.int32)
        out = V.roi_align(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                          paddle.to_tensor(bn), output_size=7,
                          spatial_scale=1.0)
        assert out.shape == [3, 3, 7, 7]
        out2 = V.roi_pool(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                          paddle.to_tensor(bn), output_size=4)
        assert out2.shape == [3, 3, 4, 4]

    def test_roi_pool_max_semantics(self):
        feat = np.zeros((1, 1, 8, 8), np.float32)
        feat[0, 0, 2, 3] = 7.0
        boxes = np.array([[0, 0, 7, 7]], np.float32)
        out = V.roi_pool(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                         paddle.to_tensor(np.array([1], np.int32)),
                         output_size=2)
        assert float(out.numpy().max()) == 7.0


class TestDeformConv:
    def test_zero_offset_equals_conv2d(self):
        rng = np.random.RandomState(2)
        x = rng.randn(2, 3, 10, 10).astype(np.float32)
        w = (rng.randn(5, 3, 3, 3) * 0.2).astype(np.float32)
        b = rng.randn(5).astype(np.float32)
        off = np.zeros((2, 2 * 9, 8, 8), np.float32)
        out = V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                              paddle.to_tensor(w), paddle.to_tensor(b))
        ref = torch.nn.functional.conv2d(
            torch.tensor(x), torch.tensor(w), torch.tensor(b)).numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)

    def test_offset_shifts_samples(self):
        # 1x1 kernel, integer offset (dy=0, dx=1) == shift left by one
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        w = np.ones((1, 1, 1, 1), np.float32)
        off = np.zeros((1, 2, 4, 4), np.float32)
        off[0, 1] = 1.0  # dx
        out = V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                              paddle.to_tensor(w))
        got = out.numpy()[0, 0]
        ref = np.arange(16, dtype=np.float32).reshape(4, 4)
        ref[:, :3] = ref[:, 1:]
        ref[:, 3] = 0.0  # out-of-image sample is ZERO (reference padding)
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_mask_and_layer_training(self):
        paddle.seed(0)
        rng = np.random.RandomState(3)
        x = paddle.to_tensor(rng.randn(1, 3, 8, 8).astype(np.float32))
        layer = V.DeformConv2D(3, 4, kernel_size=3, padding=1)
        off = paddle.to_tensor(
            (rng.randn(1, 18, 8, 8) * 0.1).astype(np.float32))
        mask = paddle.to_tensor(
            np.full((1, 9, 8, 8), 0.5, np.float32))
        out = layer(x, off, mask=mask)
        assert out.shape == [1, 4, 8, 8]
        loss = out.pow(2).mean()
        loss.backward()
        assert layer.weight.grad is not None
        assert float(np.abs(layer.weight.grad.numpy()).max()) > 0


class TestReviewRegressions:
    def test_deform_conv_padding_matches_torch(self):
        """Zero-offset deform conv with padding>0 must zero-pad (not
        edge-clamp) the border samples."""
        rng = np.random.RandomState(5)
        x = rng.randn(1, 2, 6, 6).astype(np.float32)
        w = (rng.randn(3, 2, 3, 3) * 0.3).astype(np.float32)
        off = np.zeros((1, 18, 6, 6), np.float32)
        out = V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                              paddle.to_tensor(w), padding=1)
        ref = torch.nn.functional.conv2d(torch.tensor(x), torch.tensor(w),
                                         padding=1).numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)

    def test_roi_align_adaptive_sampling(self):
        """sampling_ratio=-1 uses ceil(roi/bin) samples — more samples on a
        large ROI than sr=1, matching the reference's adaptive rule."""
        rng = np.random.RandomState(6)
        feat = rng.randn(1, 1, 32, 32).astype(np.float32)
        boxes = np.array([[0, 0, 32, 32]], np.float32)
        bn = np.array([1], np.int32)
        ad = V.roi_align(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                         paddle.to_tensor(bn), output_size=4,
                         sampling_ratio=-1, aligned=False).numpy()
        # adaptive = ceil(32/4) = 8 samples/bin → equals explicit sr=8
        sr8 = V.roi_align(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                          paddle.to_tensor(bn), output_size=4,
                          sampling_ratio=8, aligned=False).numpy()
        np.testing.assert_allclose(ad, sr8, rtol=1e-6)
        sr1 = V.roi_align(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                          paddle.to_tensor(bn), output_size=4,
                          sampling_ratio=1, aligned=False).numpy()
        assert np.abs(ad - sr1).max() > 1e-6

    def test_roi_pool_empty_bin_is_zero(self):
        feat = np.ones((1, 2, 8, 8), np.float32)
        boxes = np.array([[0, 130, 10, 140]], np.float32)  # off the map
        out = V.roi_pool(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                         paddle.to_tensor(np.array([1], np.int32)),
                         output_size=2, spatial_scale=1.0 / 16)
        np.testing.assert_array_equal(out.numpy(), 0.0)

    def test_deform_layer_registers_params(self):
        import paddle_tpu.nn as nn

        class Det(nn.Layer):
            def __init__(self):
                super().__init__()
                self.dcn = V.DeformConv2D(3, 4, kernel_size=3, padding=1)

            def forward(self, x, off):
                return self.dcn(x, off)

        m = Det()
        assert len(m.parameters()) == 2
        assert any("dcn" in k for k in m.state_dict())
