"""paddle.quantization parity (ref: python/paddle/quantization/ — QAT/PTQ
framework with quanter/observer configs; python/paddle/nn/quant weight-only
layers; SURVEY §2.2 quantization row).

TPU-native: observers collect ranges in plain jax; fake-quant is a
straight-through estimator; the deploy path converts Linear layers to
weight-only int8 backed by the Pallas dequant-matmul kernel
(paddle_tpu.ops.quant)."""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from .. import nn

__all__ = ["AbsmaxObserver", "PerChannelAbsmaxObserver", "HistObserver",
           "KLObserver", "FakeQuanterWithAbsMax",
           "FakeQuanterChannelWiseAbsMax", "QuantConfig", "QAT",
           "PTQ", "QuantedLinear", "quanted_linear_from"]


class AbsmaxObserver:
    """Tracks running absmax for activation scales (ref: observers/abs_max)."""

    def __init__(self, quant_bits: int = 8):
        self.quant_bits = quant_bits
        self.absmax = 0.0

    def observe(self, x):
        xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        self.absmax = max(self.absmax, float(jnp.max(jnp.abs(xa))))
        return x

    def scale(self) -> float:
        qmax = 2 ** (self.quant_bits - 1) - 1
        return self.absmax / qmax if self.absmax else 1.0


class PerChannelAbsmaxObserver:
    """Per-channel absmax (ref: observers AbsMaxChannelWiseWeightObserver):
    one scale per slice along ``axis`` — the weight-quant default upstream
    (per-output-channel keeps the matmul error per column independent)."""

    def __init__(self, quant_bits: int = 8, axis: int = -1):
        self.quant_bits = quant_bits
        self.axis = axis
        self.absmax = None               # jnp [C]

    def observe(self, x):
        xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        ax = self.axis % xa.ndim
        reduce_dims = tuple(i for i in range(xa.ndim) if i != ax)
        cur = jnp.max(jnp.abs(xa), axis=reduce_dims)
        self.absmax = cur if self.absmax is None else \
            jnp.maximum(self.absmax, cur)
        return x

    def scale(self):
        qmax = 2 ** (self.quant_bits - 1) - 1
        if self.absmax is None:
            return jnp.asarray(1.0)
        return jnp.maximum(self.absmax / qmax, 1e-8)


class HistObserver:
    """Histogram observer with percentile calibration (ref: observers/
    hist.py HistObserver). Collects |x| into ``bins`` buckets over a
    growing range (bucket contents are merged by an integer factor when
    the range expands, the standard re-binning trick), and calibrates the
    scale at the given percentile of the observed mass — robust to the
    outliers that make plain absmax scales waste int8 resolution."""

    def __init__(self, quant_bits: int = 8, bins: int = 2048,
                 percent: float = 0.9999):
        self.quant_bits = quant_bits
        self.bins = bins
        self.percent = percent
        self.hist = None                 # np [bins]
        self.hist_max = 0.0

    def observe(self, x):
        import numpy as np
        xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        a = np.abs(np.asarray(xa, dtype=np.float32)).ravel()
        amax = float(a.max()) if a.size else 0.0
        if amax == 0.0:
            return x
        if self.hist is None:
            self.hist_max = amax
            self.hist, _ = np.histogram(a, bins=self.bins,
                                        range=(0.0, self.hist_max))
            self.hist = self.hist.astype(np.float64)
            return x
        if amax > self.hist_max:
            # grow the range by an integer factor and merge buckets
            factor = int(np.ceil(amax / self.hist_max))
            new_max = self.hist_max * factor
            merged = np.zeros(self.bins, np.float64)
            idx = (np.arange(self.bins) / factor).astype(int)
            np.add.at(merged, idx, self.hist)
            self.hist = merged
            self.hist_max = new_max
        h, _ = np.histogram(a, bins=self.bins, range=(0.0, self.hist_max))
        self.hist += h
        return x

    def _threshold(self) -> float:
        import numpy as np
        if self.hist is None:
            return 0.0
        cum = np.cumsum(self.hist)
        total = cum[-1]
        if total == 0:
            return 0.0
        k = int(np.searchsorted(cum, self.percent * total))
        k = min(k, self.bins - 1)
        return (k + 1) / self.bins * self.hist_max

    def scale(self) -> float:
        qmax = 2 ** (self.quant_bits - 1) - 1
        t = self._threshold()
        return t / qmax if t > 0 else 1.0


class KLObserver(HistObserver):
    """Entropy (KL-divergence) calibration over the collected histogram
    (ref: observers/kl.py; the TensorRT calibration recipe): choose the
    clip threshold whose clipped-and-requantized distribution diverges
    least from the observed one."""

    def __init__(self, quant_bits: int = 8, bins: int = 2048):
        super().__init__(quant_bits=quant_bits, bins=bins)

    def _threshold(self) -> float:
        import numpy as np
        if self.hist is None:
            return 0.0
        hist = self.hist
        nq = 2 ** (self.quant_bits - 1)   # 128 target levels for int8
        if hist.sum() == 0:
            return 0.0
        best_i, best_kl = self.bins, float("inf")
        start = max(nq, self.bins // 16)
        for i in range(start, self.bins + 1, max(1, self.bins // 256)):
            p = hist[:i].copy()
            p[i - 1] += hist[i:].sum()        # clamp outliers into edge
            if p.sum() == 0:
                continue
            # quantize p's support down to nq buckets, then expand back
            idx = (np.arange(i) * nq // i)
            q_small = np.zeros(nq, np.float64)
            np.add.at(q_small, idx, hist[:i])
            counts = np.zeros(nq, np.float64)
            nonzero = (hist[:i] > 0).astype(np.float64)
            np.add.at(counts, idx, nonzero)
            q = np.zeros(i, np.float64)
            live = counts[idx] > 0
            ratio = np.divide(q_small[idx], counts[idx],
                              out=np.zeros(i, np.float64), where=live)
            q[live] = ratio[live] * (hist[:i] > 0)[live]
            ps = p / p.sum()
            qsum = q.sum()
            if qsum == 0:
                continue
            qs = q / qsum
            mask = ps > 0
            kl = float(np.sum(ps[mask] * np.log(
                ps[mask] / np.maximum(qs[mask], 1e-12))))
            if kl < best_kl:
                best_kl, best_i = kl, i
        return best_i / self.bins * self.hist_max


class FakeQuanterWithAbsMax(nn.Layer):
    """QAT fake-quant with straight-through gradients (ref:
    quanters/abs_max.py FakeQuanterWithAbsMaxObserver)."""

    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self.quant_bits = quant_bits

    def forward(self, x):
        qmax = 2 ** (self.quant_bits - 1) - 1

        def impl(a):
            scale = jnp.max(jnp.abs(a)) / qmax
            scale = jnp.maximum(scale, 1e-8)
            q = jnp.clip(jnp.round(a / scale), -qmax, qmax) * scale
            # straight-through: forward q, backward identity
            return a + jax.lax.stop_gradient(q - a)
        return apply("fake_quant_absmax", impl, [x])


class FakeQuanterChannelWiseAbsMax(nn.Layer):
    """Per-channel QAT fake-quant (ref: quanters FakeQuanterChannelWise
    AbsMaxObserver): one scale per output channel of the weight."""

    def __init__(self, quant_bits: int = 8, axis: int = -1):
        super().__init__()
        self.quant_bits = quant_bits
        self.axis = axis

    def forward(self, x):
        qmax = 2 ** (self.quant_bits - 1) - 1
        ax = self.axis

        def impl(a):
            axis = ax % a.ndim
            reduce_dims = tuple(i for i in range(a.ndim) if i != axis)
            scale = jnp.max(jnp.abs(a), axis=reduce_dims, keepdims=True)
            scale = jnp.maximum(scale / qmax, 1e-8)
            q = jnp.clip(jnp.round(a / scale), -qmax, qmax) * scale
            return a + jax.lax.stop_gradient(q - a)
        return apply("fake_quant_channel_absmax", impl, [x])


class QuantConfig:
    """ref: paddle.quantization.QuantConfig — maps layer types/names to
    quanters."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._type_configs: Dict[type, dict] = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        for t in (layer_type if isinstance(layer_type, (list, tuple))
                  else [layer_type]):
            self._type_configs[t] = {"activation": activation,
                                     "weight": weight}

    def config_for(self, layer):
        for t, cfg in self._type_configs.items():
            if isinstance(layer, t):
                return cfg
        if self.activation or self.weight:
            return {"activation": self.activation, "weight": self.weight}
        return None


class _QATLinear(nn.Layer):
    def __init__(self, inner: nn.Linear, a_quanter, w_quanter):
        super().__init__()
        self.inner = inner
        self.a_q = a_quanter
        self.w_q = w_quanter

    def forward(self, x):
        if self.a_q is not None:
            x = self.a_q(x)
        w = self.inner.weight
        if self.w_q is not None:
            w = self.w_q(w)
        from ..nn import functional as F
        return F.linear(x, w, self.inner.bias)


class QAT:
    """Quantization-aware training flow (ref: paddle.quantization.QAT)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace: bool = False):
        for name, sub in list(model.named_sublayers()):
            for cname, child in list(sub.__dict__["_sub_layers"].items()):
                cfg = self.config.config_for(child)
                if cfg and isinstance(child, nn.Linear):
                    a_q = cfg["activation"]() if cfg["activation"] else None
                    w_q = cfg["weight"]() if cfg["weight"] else None
                    sub.add_sublayer(cname, _QATLinear(child, a_q, w_q))
        # top-level children too
        for cname, child in list(model.__dict__["_sub_layers"].items()):
            cfg = self.config.config_for(child)
            if cfg and isinstance(child, nn.Linear):
                a_q = cfg["activation"]() if cfg["activation"] else None
                w_q = cfg["weight"]() if cfg["weight"] else None
                model.add_sublayer(cname, _QATLinear(child, a_q, w_q))
        return model


class QuantedLinear(nn.Layer):
    """Deployed weight-only int8 linear over the Pallas dequant-matmul."""

    def __init__(self, qweight, scale, bias=None):
        super().__init__()
        self.qweight = qweight
        self.scale = scale
        self.bias = bias

    def forward(self, x):
        from ..incubate.nn.functional import weight_only_linear
        return weight_only_linear(x, self.qweight, bias=self.bias,
                                  weight_scale=self.scale)


def quanted_linear_from(linear: nn.Linear) -> QuantedLinear:
    from ..ops.quant import weight_quantize
    qw, sc = weight_quantize(linear.weight._data)
    return QuantedLinear(Tensor(qw), Tensor(sc), linear.bias)


class PTQ:
    """Post-training quantization flow (ref: paddle.quantization.PTQ):
    observe activations on calibration batches, then convert Linears to
    weight-only int8."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()
        self.observers: Dict[str, object] = {}

    def quantize(self, model, inplace: bool = False):
        self._hooks = []
        obs_cls = self.config.activation or AbsmaxObserver
        probe = obs_cls()   # class OR zero-arg factory (functools.partial
                            # for configured observers, e.g.
                            # partial(KLObserver, bins=512))
        if not hasattr(probe, "observe"):
            raise TypeError(
                f"PTQ needs an OBSERVER (has .observe/.scale) for "
                f"QuantConfig.activation, got {type(probe).__name__}; "
                "fake-quanters (FakeQuanterWithAbsMax etc.) are QAT "
                "layers — use them with QAT, not PTQ")
        for name, sub in model.named_sublayers():
            if isinstance(sub, nn.Linear):
                obs = obs_cls()
                self.observers[name] = obs

                def mk(o):
                    def hook(layer, inputs):
                        o.observe(inputs[0])
                        return None
                    return hook
                self._hooks.append(sub.register_forward_pre_hook(mk(obs)))
        return model

    def convert(self, model, inplace: bool = False):
        for h in getattr(self, "_hooks", []):
            h.remove()
        obs_by_layer = {}
        for name, sub in model.named_sublayers():
            if name in self.observers:
                obs_by_layer[id(sub)] = self.observers[name]

        def convert_children(parent):
            for cname, child in list(parent.__dict__["_sub_layers"].items()):
                if isinstance(child, nn.Linear):
                    ql = quanted_linear_from(child)
                    obs = obs_by_layer.get(id(child))
                    if obs is not None:
                        # calibrated activation scale rides with the layer
                        # (consumed by a full-int8 deploy; recorded even on
                        # the weight-only path so calibration is auditable)
                        ql.act_scale = obs.scale()
                    parent.add_sublayer(cname, ql)
                else:
                    convert_children(child)
        convert_children(model)
        return model
