"""Launcher CLI: spawn, rank env, workerlogs, restart policy (SURVEY P14)."""

import os
import textwrap

from paddle_tpu.distributed.launch import launch


def _write_script(tmp_path, body):
    p = tmp_path / "trainer.py"
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_spawn_two_ranks_env_and_logs(tmp_path):
    out = tmp_path / "env"
    out.mkdir()
    script = _write_script(tmp_path, f"""
        import os, json
        rank = os.environ["PADDLE_TRAINER_ID"]
        keep = {{k: v for k, v in os.environ.items()
                if k.startswith(("PADDLE_", "JAX_", "COORDINATOR"))}}
        with open(os.path.join({str(out)!r}, rank + ".json"), "w") as f:
            json.dump(keep, f)
        print("rank", rank, "done")
    """)
    rc = launch(["--nproc_per_node", "2", "--log_dir",
                 str(tmp_path / "log"), script])
    assert rc == 0
    import json
    e0 = json.load(open(out / "0.json"))
    e1 = json.load(open(out / "1.json"))
    assert e0["PADDLE_TRAINERS_NUM"] == "2"
    assert e1["PADDLE_TRAINER_ID"] == "1"
    assert e0["JAX_NUM_PROCESSES"] == "2"
    assert e0["COORDINATOR_ADDRESS"] == e1["COORDINATOR_ADDRESS"]
    assert len(e0["PADDLE_TRAINER_ENDPOINTS"].split(",")) == 2
    # per-rank logs written (ref: workerlog.N)
    log0 = (tmp_path / "log" / "workerlog.0").read_text()
    assert "rank 0 done" in log0
    assert "rank 1 done" in (tmp_path / "log" / "workerlog.1").read_text()


def test_nonzero_exit_propagates(tmp_path):
    script = _write_script(tmp_path, """
        import sys
        sys.exit(3)
    """)
    rc = launch(["--nproc_per_node", "1", "--log_dir",
                 str(tmp_path / "log"), script])
    assert rc == 3


def test_restart_policy_recovers(tmp_path):
    sentinel = tmp_path / "came_before"
    script = _write_script(tmp_path, f"""
        import os, sys
        s = {str(sentinel)!r}
        if not os.path.exists(s):
            open(s, "w").write("x")
            sys.exit(1)   # first attempt fails
        print("second attempt ok")
    """)
    rc = launch(["--nproc_per_node", "1", "--max_restarts", "1",
                 "--log_dir", str(tmp_path / "log"), script])
    assert rc == 0
    assert "second attempt ok" in (tmp_path / "log" / "workerlog.0").read_text()


def test_elastic_manager_membership():
    from paddle_tpu.native import TCPStore
    from paddle_tpu.distributed.launch import ElasticManager
    s = TCPStore(is_master=True, world_size=2)
    try:
        m0 = ElasticManager(s, node_rank=0, ttl=5.0)
        m1 = ElasticManager(s, node_rank=1, ttl=5.0)
        m0.heartbeat()
        assert m0.alive_nodes(2) == [0]
        assert m0.membership_changed(expected=2)
        m1.heartbeat()
        assert m0.alive_nodes(2) == [0, 1]
        assert not m0.membership_changed(expected=2)
    finally:
        s.close()


def test_heartbeat_payload_channel_tolerated():
    """The '|'-suffix payload channel (used by the collective watchdog to
    publish flight progress) must not break liveness parsing."""
    from paddle_tpu.native import TCPStore
    from paddle_tpu.distributed.launch import ElasticManager
    s = TCPStore(is_master=True, world_size=1)
    try:
        m = ElasticManager(s, node_rank=0, ttl=5.0)
        m.heartbeat(payload="rank=0,seq=7,op=all_reduce")
        assert m.alive_nodes(1) == [0]
        raw = s.get("heartbeat/0").decode()
        assert raw.split("|", 1)[1] == "rank=0,seq=7,op=all_reduce"
    finally:
        s.close()


def test_claim_slot_rechecks_racing_joiner():
    """Two joiners race for the same stale slot: the loser's post-add
    re-check sees the winner's fresh heartbeat and must move on to the
    next slot instead of double-claiming."""
    import time as _time
    from paddle_tpu.native import TCPStore
    from paddle_tpu.distributed.launch import ElasticManager

    class RacingStore:
        """Store wrapper that simulates a rival joiner winning slot 0
        between our claim-counter add and the heartbeat re-check."""

        def __init__(self, inner):
            self._inner = inner
            self._raced = False

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def add(self, key, amount):
            token = self._inner.add(key, amount)
            if key == "claim/0" and not self._raced:
                self._raced = True
                self._inner.set("heartbeat/0", str(_time.time()))
            return token

    s = TCPStore(is_master=True, world_size=1)
    try:
        m = ElasticManager(RacingStore(s), node_rank=99, ttl=5.0,
                           min_nodes=1, max_nodes=3)
        slot = m.claim_slot()
        assert slot == 1                    # slot 0 lost to the rival
        assert m.node_rank == 1
        assert m.alive_nodes(2) == [0, 1]   # both heartbeating now
        m.heartbeat()                       # our token is current: no raise
    finally:
        s.close()


def test_heartbeat_slot_theft_fence():
    """A node that paused past the TTL and lost its slot to a newer
    claimant must see the moved claim counter and exit, not keep
    heartbeating a slot it no longer owns (split-brain fence)."""
    import pytest
    from paddle_tpu.native import TCPStore
    from paddle_tpu.distributed.launch import ElasticManager
    s = TCPStore(is_master=True, world_size=1)
    try:
        m = ElasticManager(s, node_rank=0, ttl=5.0, min_nodes=1,
                           max_nodes=2)
        m.register_slot()
        m.heartbeat()                       # own token: fine
        s.add("claim/0", 1)                 # a newer owner claims the slot
        with pytest.raises(RuntimeError, match="reclaimed"):
            m.heartbeat()
    finally:
        s.close()


def test_restart_banner_marks_each_attempt(tmp_path):
    """Satellite bugfix: workerlog.N is opened append-mode across
    restarts, so every (re)spawn writes a '=== restart N / gen G ==='
    marker separating the attempts."""
    sentinel = tmp_path / "came_before"
    script = _write_script(tmp_path, f"""
        import os, sys
        s = {str(sentinel)!r}
        if not os.path.exists(s):
            open(s, "w").write("x")
            sys.exit(1)
        print("attempt two ok")
    """)
    rc = launch(["--nproc_per_node", "1", "--max_restarts", "1",
                 "--log_dir", str(tmp_path / "log"), script])
    assert rc == 0
    log = (tmp_path / "log" / "workerlog.0").read_text()
    assert "=== restart 0 / gen 0 ===" in log
    assert "=== restart 1 / gen 0 ===" in log
    # the failing first attempt's lines sit under the first banner
    assert log.index("=== restart 0") < log.index("=== restart 1") \
        < log.index("attempt two ok")


def test_flight_report_merged_on_terminal_failure(tmp_path):
    """On terminal child failure the controller collects per-rank
    flightdump.*.json from the log dir into one flight_report.json naming
    the lagging rank (ISSUE 3 post-mortem merge)."""
    import json
    script = _write_script(tmp_path, """
        import json, os, sys
        # stand in for the watchdog: write this rank's flight dump, then
        # die the way a hung collective does after CollectiveTimeout
        rank = int(os.environ["PADDLE_TRAINER_ID"])
        seqs = {0: 2, 1: 1}[rank]
        recs = [{"seq": i + 1, "op": "all_reduce", "shapes": [[4]],
                 "dtypes": ["float32"], "bytes": 16, "axis": "dp",
                 "start": 0.0, "end": 0.1, "duration_s": 0.1,
                 "status": "ok"} for i in range(seqs)]
        dump = {"version": 1, "rank": rank, "last_completed_seq": seqs,
                "records": recs}
        path = os.path.join(os.environ["PADDLE_LOG_DIR"],
                            f"flightdump.{rank}.json")
        with open(path, "w") as f:
            json.dump(dump, f)
        # wait for the peer's dump so the controller can't reap one rank
        # before the other has written (both must appear in the report)
        import time
        peer = os.path.join(os.environ["PADDLE_LOG_DIR"],
                            f"flightdump.{1 - rank}.json")
        for _ in range(200):
            if os.path.exists(peer):
                break
            time.sleep(0.05)
        sys.exit(7)
    """)
    rc = launch(["--nproc_per_node", "2", "--max_restarts", "0",
                 "--log_dir", str(tmp_path / "log"), script])
    assert rc == 7
    report = json.load(open(tmp_path / "log" / "flight_report.json"))
    assert report["world"] == 2
    assert report["exit_code"] == 7
    assert report["lagging_rank"] == 1
    assert report["last_completed_seq"] == {"0": 2, "1": 1} or \
        report["last_completed_seq"] == {0: 2, 1: 1}
    fd = report["first_divergence"]
    assert fd["seq"] == 2 and fd["reason"] == "missing_rank"


def test_fault_injection_sigkill_worker_recovers(tmp_path):
    """Kill-a-worker fault injection (SURVEY §5.3): rank 1 SIGKILLs itself
    mid-run on the first attempt; the watch loop must tear the pod down and
    relaunch it, and the retry completes on all ranks."""
    sentinel = tmp_path / "already_died"
    done = tmp_path / "done"
    done.mkdir()
    script = _write_script(tmp_path, f"""
        import os, signal, time
        rank = os.environ["PADDLE_TRAINER_ID"]
        s = {str(sentinel)!r}
        if rank == "1" and not os.path.exists(s):
            open(s, "w").write("x")
            os.kill(os.getpid(), signal.SIGKILL)  # simulated host failure
        if rank == "0" and not os.path.exists(s):
            time.sleep(30)  # would hang forever if the pod were not torn down
        open(os.path.join({str(done)!r}, rank), "w").write("ok")
        print("rank", rank, "finished")
    """)
    import time
    t0 = time.time()
    rc = launch(["--nproc_per_node", "2", "--max_restarts", "1",
                 "--log_dir", str(tmp_path / "log"), script])
    assert rc == 0
    # rank 0's first attempt was killed by the controller (not after 30s)
    assert time.time() - t0 < 25
    assert "rank 0 finished" in (tmp_path / "log" / "workerlog.0").read_text()
    assert "rank 1 finished" in (tmp_path / "log" / "workerlog.1").read_text()
    # both ranks completed the retry attempt
    assert (done / "0").exists() and (done / "1").exists()
