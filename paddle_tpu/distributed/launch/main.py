"""Launcher CLI entry (ref: python/paddle/distributed/launch/main.py).

Usage parity:
    python -m paddle_tpu.distributed.launch \
        [--nnodes N[:M]] [--node_rank R] [--nproc_per_node P] \
        [--master HOST:PORT] [--log_dir DIR] [--devices 0,1] \
        [--max_restarts K] training_script [args...]
"""

from __future__ import annotations

import argparse
import sys

from .controllers import CollectiveController

__all__ = ["launch", "main"]


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="paddle-parity multi-host launcher for TPU pods")
    p.add_argument("--nnodes", default="1",
                   help="node count, or MIN:MAX for elastic")
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per host (TPU: one per host)")
    p.add_argument("--master", default=None,
                   help="HOST:PORT of the rendezvous store (rank-0 host)")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--devices", default=None,
                   help="visible accelerator ids, e.g. '0,1'")
    p.add_argument("--max_restarts", type=int, default=0)
    p.add_argument("--rdzv_timeout", type=float, default=120.0)
    p.add_argument("--poll_interval", type=float, default=0.2)
    p.add_argument("--elastic_join", action="store_true",
                   help="join a RUNNING elastic job (--nnodes MIN:MAX) "
                        "by claiming a free membership slot; the leader "
                        "relaunches the pod with this node included")
    p.add_argument("--elastic_ttl", type=float, default=10.0,
                   help="membership heartbeat TTL seconds")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p


def launch(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    return CollectiveController(args).run()


def main() -> None:  # console entry
    sys.exit(launch())
