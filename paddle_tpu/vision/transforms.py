"""paddle.vision.transforms parity (ref: python/paddle/vision/transforms/).

Host-side preprocessing on numpy arrays (HWC uint8/float), emitting CHW
float arrays for the NCHW model zoo — matching the reference's default
pipeline. Resize uses jax.image on host CPU.
"""

from __future__ import annotations

import numbers
import random
from typing import List, Sequence

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "Pad"]


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor:
    """HWC [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        arr = arr.astype(np.float32)
        if arr.max() > 1.0:
            arr = arr / 255.0
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize:
    def __init__(self, mean, std, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


def _size2hw(size):
    if isinstance(size, numbers.Number):
        return int(size), int(size)
    return int(size[0]), int(size[1])


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.h, self.w = _size2hw(size)
        self.interpolation = interpolation

    def __call__(self, img):
        import jax
        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and \
            arr.shape[0] < arr.shape[-1]
        if arr.ndim == 2:
            out = jax.image.resize(arr, (self.h, self.w), self.interpolation)
        elif chw:
            out = jax.image.resize(arr, (arr.shape[0], self.h, self.w),
                                   self.interpolation)
        else:
            out = jax.image.resize(arr, (self.h, self.w, arr.shape[2]),
                                   self.interpolation)
        return np.asarray(out)


class CenterCrop:
    def __init__(self, size):
        self.h, self.w = _size2hw(size)

    def __call__(self, img):
        arr = np.asarray(img)
        H, W = arr.shape[-3:-1] if arr.ndim == 3 and arr.shape[0] in (1, 3) \
            else arr.shape[:2]
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and \
            arr.shape[0] < arr.shape[-1]
        if chw:
            H, W = arr.shape[1], arr.shape[2]
            top = max(0, (H - self.h) // 2)
            left = max(0, (W - self.w) // 2)
            return arr[:, top:top + self.h, left:left + self.w]
        H, W = arr.shape[0], arr.shape[1]
        top = max(0, (H - self.h) // 2)
        left = max(0, (W - self.w) // 2)
        return arr[top:top + self.h, left:left + self.w]


class RandomCrop:
    def __init__(self, size):
        self.h, self.w = _size2hw(size)

    def __call__(self, img):
        arr = np.asarray(img)
        H, W = arr.shape[0], arr.shape[1]
        top = random.randint(0, max(0, H - self.h))
        left = random.randint(0, max(0, W - self.w))
        return arr[top:top + self.h, left:left + self.w]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if random.random() < self.prob:
            arr = np.asarray(img)
            # width axis: 1 for HW/HWC, 2 for CHW
            waxis = 2 if (arr.ndim == 3 and arr.shape[0] in (1, 3)) else 1
            return np.flip(arr, axis=waxis).copy()
        return img


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[::-1]
        return img


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class Pad:
    def __init__(self, padding, fill=0):
        self.padding = padding if isinstance(padding, (list, tuple)) \
            else (padding,) * 4  # l, t, r, b
        self.fill = fill

    def __call__(self, img):
        arr = np.asarray(img)
        l, t, r, b = self.padding
        pads = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
        return np.pad(arr, pads, constant_values=self.fill)
