"""Global radix prefix cache (serving.prefix_cache): trie insert/
lookup/evict unit behavior over pinned allocator pages, engine-level
multi-tenant prefill skip with exactness, pool-pressure eviction, the
enable_prefix_cache knob, and the no-leaked-pins regression on
admission-refusal / queue-expiry paths."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import resilience as res
from paddle_tpu import serving as srv
from paddle_tpu.generation import generate_cached
from paddle_tpu.inference import Config
from paddle_tpu.serving import PageBlockAllocator, PrefixCache, ServingEngine


def _metric(name):
    fam = srv.metrics().get(name)
    if not fam or not fam["series"]:
        return 0.0
    return fam["series"][0]["value"]


def _solo(model, prompt, max_new):
    out, _ = generate_cached(model, paddle.to_tensor(prompt[None]),
                             max_new_tokens=max_new,
                             decode_strategy="greedy_search")
    return out.numpy()[0]


def _prefill(a, cache, sid, prompt):
    """Simulate engine prefill: allocate, extend to the full prompt,
    insert the full pages into the trie."""
    a.allocate(sid, len(prompt))
    a.extend(sid, len(prompt))
    cache.insert(prompt, a.seq_pages(sid))


class TestTrieUnit:
    def test_insert_lookup_roundtrip_page_granular(self):
        a = PageBlockAllocator(num_pages=17, page_size=4, pages_per_seq=4)
        cache = PrefixCache(a)
        prompt = list(range(100, 111))            # 11 tokens: 2 full pages
        _prefill(a, cache, "s", prompt)
        assert cache.pages == 2                   # 11 // 4
        a.free("s")
        m = cache.lookup(prompt)                  # cap (11-1)//4 = 2
        assert m.tokens == 8 and len(m.pages) == 2
        # an extension matches the same prefix; a divergence stops early
        m2 = cache.lookup(prompt + [7, 7, 7, 7, 7])
        assert m2.tokens == 8
        m3 = cache.lookup([100, 101, 102, 103, 9, 9, 9, 9, 9])
        assert m3.tokens == 4 and m3.pages == m.pages[:1]
        for mm in (m, m2, m3):
            mm.release()
        cache.flush()
        assert a.free_pages == 16

    def test_last_prompt_token_never_matched(self):
        # an exactly-page-aligned prompt still recomputes its last token
        a = PageBlockAllocator(num_pages=9, page_size=4, pages_per_seq=4)
        cache = PrefixCache(a)
        prompt = list(range(8))
        _prefill(a, cache, "s", prompt)
        assert cache.pages == 2
        a.free("s")
        m = cache.lookup(prompt)                  # cap (8-1)//4 = 1
        assert m.tokens == 4 and len(m.pages) == 1
        m.release()
        cache.flush()

    def test_first_writer_wins(self):
        a = PageBlockAllocator(num_pages=17, page_size=4, pages_per_seq=4)
        cache = PrefixCache(a)
        prompt = list(range(8))
        _prefill(a, cache, "s1", prompt)
        m1 = cache.lookup(prompt + [1, 2, 3, 4])
        _prefill(a, cache, "s2", prompt)          # same prefix again
        assert cache.pages == 2                   # nothing re-inserted
        m2 = cache.lookup(prompt + [1, 2, 3, 4])
        assert m2.pages == m1.pages               # s1's physical pages
        m1.release()
        m2.release()
        a.free("s1")
        a.free("s2")
        cache.flush()
        assert a.free_pages == 16

    def test_match_pin_protects_lookup_to_adopt_window(self):
        a = PageBlockAllocator(num_pages=9, page_size=4, pages_per_seq=4)
        cache = PrefixCache(a)
        prompt = list(range(12))
        _prefill(a, cache, "s", prompt)
        a.free("s")
        m = cache.lookup(prompt)
        assert m.tokens == 8
        # a flush between lookup and adopt evicts the trie NODES but the
        # match pin keeps the physical pages alive for the adopter
        cache.flush()
        assert cache.pages == 0
        for pg in m.pages:
            assert a.refcount(pg) >= 1
        a.adopt("c", m.pages, share_tokens=8, total_tokens=12)
        m.release()
        assert a.seq_length("c") == 8
        a.free("c")
        assert a.free_pages == 8

    def test_lru_eviction_order_and_cascade(self):
        a = PageBlockAllocator(num_pages=17, page_size=4, pages_per_seq=4)
        cache = PrefixCache(a)
        pa = list(range(0, 12))                   # chain of 3 pages
        pb = list(range(100, 108))                # separate 2-page chain
        _prefill(a, cache, "a", pa)
        _prefill(a, cache, "b", pb)
        a.free("a")
        a.free("b")
        assert cache.pages == 5
        # touch ALL of A's pages (lookup caps one token short of the
        # prompt, so probe with an extension): A becomes the warmest
        cache.lookup(pa + [1]).release()
        assert cache.evict(1) == 1                # evicts B's cold leaf
        assert cache.match_length(pb) == 4        # B's root page remains
        assert cache.match_length(pa + [1]) == 12
        # cascade: draining the rest walks leaf -> parent -> root child
        assert cache.evict(10) == 4
        assert cache.pages == 0
        assert a.free_pages == 16

    def test_eviction_skips_pages_shared_by_live_sequences(self):
        a = PageBlockAllocator(num_pages=9, page_size=4, pages_per_seq=4)
        cache = PrefixCache(a)
        prompt = list(range(8))
        _prefill(a, cache, "s", prompt)           # "s" still live
        assert cache.evictable_pages() == 0
        assert cache.evict(8) == 0
        assert cache.pages == 2
        a.free("s")
        assert cache.evictable_pages() == 1       # the leaf
        assert cache.evict(8) == 2                # leaf, then its parent
        assert a.free_pages == 8

    def test_metrics_roundtrip(self):
        a = PageBlockAllocator(num_pages=9, page_size=4, pages_per_seq=4)
        cache = PrefixCache(a)
        base = {k: _metric(f"serving.prefix_cache.{k}")
                for k in ("hits", "misses", "evicted_pages",
                          "shared_tokens")}
        prompt = list(range(12))
        cache.lookup(prompt).release()            # miss: trie empty
        _prefill(a, cache, "s", prompt)
        a.free("s")
        m = cache.lookup(prompt)                  # hit: 2 pages
        cache.note_adopted(m.tokens)
        m.release()
        cache.flush()
        assert _metric("serving.prefix_cache.hits") == base["hits"] + 1
        assert _metric("serving.prefix_cache.misses") == base["misses"] + 1
        assert _metric("serving.prefix_cache.evicted_pages") \
            == base["evicted_pages"] + 3
        assert _metric("serving.prefix_cache.shared_tokens") \
            == base["shared_tokens"] + 8
        assert _metric("serving.prefix_cache.pages") == 0


class TestEnginePrefixCache:
    @pytest.fixture(scope="class")
    def model(self):
        from paddle_tpu.models.llama import (LlamaForCausalLM,
                                             llama_tiny_config)
        paddle.seed(0)
        m = LlamaForCausalLM(llama_tiny_config(num_hidden_layers=1))
        m.eval()
        return m

    def test_multitenant_shared_system_prompt_skip(self, model):
        # acceptance: 16 tenants, one shared system prompt — >= 80% of
        # prompt tokens skip prefill via the trie, outputs stay exact.
        # prefix_sharing (live-donor fork) is OFF so the cache is the
        # only sharing mechanism under test.
        V = model.config.vocab_size
        rng = np.random.RandomState(42)
        system = rng.randint(0, V, 24).astype(np.int32)   # 6 full pages
        eng = ServingEngine(model, max_slots=2, page_size=4,
                            prefill_chunk=8, prefix_sharing=False)
        shared = hits = 0
        hits0 = _metric("serving.prefix_cache.hits")
        total_prompt = 0
        for t in range(16):
            tail = rng.randint(0, V, 3).astype(np.int32)
            prompt = np.concatenate([system, tail])
            total_prompt += prompt.size
            r = eng.add_request(prompt, max_new_tokens=3,
                                tenant=f"tenant{t}")
            out = eng.run_to_completion()[r.request_id]
            np.testing.assert_array_equal(out, _solo(model, prompt, 3))
            shared += r.shared_tokens
            if r.shared_tokens:
                assert r._share_source == "cache"
        assert shared / total_prompt >= 0.80
        assert shared == 15 * 24                  # all but the first
        assert _metric("serving.prefix_cache.hits") - hits0 >= 15
        assert all(v == 1 for v in eng.program_cache_sizes().values())
        # teardown leaves only trie pins; flush returns the whole pool
        eng.prefix_cache.flush()
        assert eng.allocator.free_pages == eng.allocator.num_pages - 1

    def test_cache_off_knob(self, model):
        V = model.config.vocab_size
        rng = np.random.RandomState(3)
        prompt = rng.randint(0, V, 12).astype(np.int32)
        eng = ServingEngine(model, max_slots=2, page_size=4,
                            prefill_chunk=4, prefix_sharing=False,
                            enable_prefix_cache=False)
        assert eng.prefix_cache is None
        r1 = eng.add_request(prompt, max_new_tokens=3)
        out = eng.run_to_completion()
        r2 = eng.add_request(prompt.copy(), max_new_tokens=3)
        out.update(eng.run_to_completion())
        assert r2.shared_tokens == 0
        np.testing.assert_array_equal(out[r1.request_id],
                                      out[r2.request_id])
        np.testing.assert_array_equal(out[r2.request_id],
                                      _solo(model, prompt, 3))

    def test_config_set_prefix_cache(self, model):
        cfg = Config()
        cfg.set_prefix_cache(False)
        eng = ServingEngine(model, max_slots=1, page_size=4, config=cfg)
        assert eng.prefix_cache is None
        cfg2 = Config()
        cfg2.set_prefix_cache(True)
        eng2 = ServingEngine(model, max_slots=1, page_size=4, config=cfg2)
        assert eng2.prefix_cache is not None

    def test_pool_pressure_evicts_cold_prefixes_exactly(self, model):
        # pool too small to keep every tenant's prefix cached: admission
        # evicts cold trie pages and retries; outputs stay exact
        V = model.config.vocab_size
        rng = np.random.RandomState(11)
        eng = ServingEngine(model, max_slots=1, page_size=4,
                            prefill_chunk=4, num_pages=10,
                            max_context=16, prefix_sharing=False)
        ev0 = _metric("serving.prefix_cache.evicted_pages")
        for i in range(4):
            prompt = rng.randint(0, V, 12).astype(np.int32)
            r = eng.add_request(prompt, max_new_tokens=3)
            out = eng.run_to_completion()[r.request_id]
            np.testing.assert_array_equal(out, _solo(model, prompt, 3))
        assert _metric("serving.prefix_cache.evicted_pages") > ev0
        eng.prefix_cache.flush()
        assert eng.allocator.free_pages == 9

    def test_refusal_paths_release_pins(self, model):
        # regression (ISSUE 10 small fix): pool-exhaustion refusals and
        # queue expiry must release the admission lookup's trie pins —
        # after the trace drains, only trie nodes hold pages and a
        # flush returns the ENTIRE pool to the free list
        V = model.config.vocab_size
        rng = np.random.RandomState(13)
        cfg = Config()
        cfg.set_admission(3, queue_timeout_s=0.05)
        base = rng.randint(0, V, 8).astype(np.int32)
        eng = ServingEngine(model, max_slots=2, page_size=4,
                            prefill_chunk=4, num_pages=7,
                            max_context=16, config=cfg)
        results = {}
        reqs = []
        for i in range(3):
            tail = rng.randint(0, V, 3).astype(np.int32)
            prompt = np.concatenate([base[:8 - i], tail])
            reqs.append(eng.add_request(prompt, max_new_tokens=3))
        results.update(eng.run_to_completion())
        outcomes = [type(results[r.request_id]).__name__ for r in reqs]
        assert not eng.scheduler.has_work()
        a = eng.allocator
        assert a.stats()["sequences"] == 0
        # every live page is held by the trie alone (refcount == pins)
        for pg in range(1, a.num_pages):
            assert a.refcount(pg) == a.pinned(pg), (pg, outcomes)
        eng.prefix_cache.flush()
        assert a.free_pages == a.num_pages - 1
