"""paddle.fft parity (ref: python/paddle/fft.py — cuFFT/pocketfft backends;
SURVEY §2.2 misc numerics). On TPU, XLA lowers FFTs natively."""

from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import apply
from .core.tensor import Tensor

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2",
           "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn", "fftshift",
           "ifftshift", "fftfreq", "rfftfreq"]


def _mk(name, fn):
    def op(x, n=None, axis=-1, norm="backward", name_arg=None):
        return apply(name, lambda a: fn(a, n, axis, norm), [x])
    op.__name__ = name
    return op


def _mk2(name, fn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name_arg=None):
        return apply(name, lambda a: fn(a, s, axes, norm), [x])
    op.__name__ = name
    return op


fft = _mk("fft", lambda a, n, ax, nm: jnp.fft.fft(a, n, ax, nm))
ifft = _mk("ifft", lambda a, n, ax, nm: jnp.fft.ifft(a, n, ax, nm))
rfft = _mk("rfft", lambda a, n, ax, nm: jnp.fft.rfft(a, n, ax, nm))
irfft = _mk("irfft", lambda a, n, ax, nm: jnp.fft.irfft(a, n, ax, nm))
hfft = _mk("hfft", lambda a, n, ax, nm: jnp.fft.hfft(a, n, ax, nm))
ihfft = _mk("ihfft", lambda a, n, ax, nm: jnp.fft.ihfft(a, n, ax, nm))
fft2 = _mk2("fft2", lambda a, s, ax, nm: jnp.fft.fft2(a, s, ax, nm))
ifft2 = _mk2("ifft2", lambda a, s, ax, nm: jnp.fft.ifft2(a, s, ax, nm))
rfft2 = _mk2("rfft2", lambda a, s, ax, nm: jnp.fft.rfft2(a, s, ax, nm))
irfft2 = _mk2("irfft2", lambda a, s, ax, nm: jnp.fft.irfft2(a, s, ax, nm))
fftn = _mk2("fftn", lambda a, s, ax, nm: jnp.fft.fftn(a, s, ax, nm))
ifftn = _mk2("ifftn", lambda a, s, ax, nm: jnp.fft.ifftn(a, s, ax, nm))
rfftn = _mk2("rfftn", lambda a, s, ax, nm: jnp.fft.rfftn(a, s, ax, nm))
irfftn = _mk2("irfftn", lambda a, s, ax, nm: jnp.fft.irfftn(a, s, ax, nm))


def fftshift(x, axes=None, name=None):
    return apply("fftshift", lambda a: jnp.fft.fftshift(a, axes), [x])


def ifftshift(x, axes=None, name=None):
    return apply("ifftshift", lambda a: jnp.fft.ifftshift(a, axes), [x])


def fftfreq(n, d=1.0, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or jnp.float32))
