"""Einsum (ref: python/paddle/tensor/einsum.py — paddle ships its own planner;
on TPU we delegate to jnp.einsum, whose contractions XLA maps onto the MXU)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["einsum"]


def einsum(equation, *operands):
    ops = [o for o in operands]
    return apply("einsum",
                 lambda *arrs: jnp.einsum(equation, *arrs), list(ops))
