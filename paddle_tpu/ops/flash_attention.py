"""Attention kernels.

Reference parity: paddle/phi/kernels/gpu/flash_attn_kernel.cu (FlashAttention2
fwd/bwd) and python/paddle/nn/functional/flash_attention.py. On TPU the fused
path is a Pallas flash kernel (added at the L6 milestone in
paddle_tpu/ops/pallas/); this module always provides `sdpa_reference`, the
XLA composite that (a) is the correctness oracle for the Pallas kernel per
SURVEY §4.1, and (b) is already MXU-efficient for moderate sequence lengths
because XLA fuses the softmax chain.

Layout convention (paddle): [batch, seq, num_heads, head_dim].
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["sdpa_reference", "flash_attention"]


def sdpa_reference(q, k, v, mask=None, causal: bool = False,
                   dropout_p: float = 0.0, scale: Optional[float] = None):
    """[B,S,H,D] scaled-dot-product attention, bf16-safe (f32 softmax)."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    if scale is None:
        scale = D ** -0.5
    qh = jnp.swapaxes(q, 1, 2)  # [B,H,S,D]
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    logits = logits.astype(jnp.float32)
    if causal:
        cm = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        logits = jnp.where(cm, logits, jnp.asarray(-1e30, logits.dtype))
    if mask is not None:
        m = jnp.asarray(mask)
        if m.dtype == jnp.bool_:
            logits = jnp.where(m, logits, jnp.asarray(-1e30, logits.dtype))
        else:
            logits = logits + m.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0:
        from ..framework.random import next_key
        keep = jax.random.bernoulli(next_key(), 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p),
                          jnp.zeros((), probs.dtype))
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)  # back to [B,S,H,D]


def _tpu_flash_available() -> bool:
    return jax.default_backend() == "tpu"


def _largest_dividing_block(S: int) -> int:
    """Largest multiple-of-128 block <= 512 that divides S (kernel contract:
    seq must be divisible by the chosen block)."""
    for b in (512, 384, 256, 128):
        if S % b == 0:
            return b
    return 0


def _flash_block_sizes(Sq: int, Sk: int):
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes
    bq = _largest_dividing_block(Sq)
    bk = _largest_dividing_block(Sk)
    return BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk,
        block_k_dkv=bk, block_q_dkv=bq,
        block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq)


def sdpa(q, k, v, mask=None, causal: bool = False, dropout_p: float = 0.0,
         scale: Optional[float] = None):
    """Routing SDPA on raw [B,S,H,D] arrays: Pallas flash kernel on TPU
    (ref parity: FlashAttnKernel, paddle/phi/kernels/gpu/flash_attn_kernel.cu
    — here the fused device kernel is the in-tree Pallas TPU flash attention
    rather than a .cu file), XLA composite elsewhere. The XLA composite
    (`sdpa_reference`) is the correctness oracle per SURVEY §4.1."""
    D = q.shape[-1]
    if scale is None:
        scale = D ** -0.5
    use_flash = (_tpu_flash_available() and mask is None and dropout_p == 0.0
                 and q.shape[1] == k.shape[1]
                 and _largest_dividing_block(q.shape[1]) > 0
                 and ((D <= 128 and D % 64 == 0) or D % 128 == 0))
    if use_flash:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as _pallas_flash)
        qh = jnp.swapaxes(q, 1, 2)  # [B,H,S,D]
        kh = jnp.swapaxes(k, 1, 2)
        vh = jnp.swapaxes(v, 1, 2)
        out = _pallas_flash(qh, kh, vh, causal=causal, sm_scale=scale,
                            block_sizes=_flash_block_sizes(q.shape[1],
                                                           k.shape[1]))
        return jnp.swapaxes(out, 1, 2)
    return sdpa_reference(q, k, v, mask=mask, causal=causal,
                          dropout_p=dropout_p, scale=scale)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, name=None):
    """paddle.nn.functional.flash_attention.flash_attention parity wrapper."""
    from ..core.dispatch import apply
    def impl(q, k, v):
        return sdpa(q, k, v, causal=causal, dropout_p=dropout)
    out = apply("flash_attention", impl, [query, key, value])
    return out, None  # (out, softmax) — softmax only materialized on request
