"""Device mesh & hybrid topology (ref: python/paddle/distributed/fleet/base/
topology.py `HybridCommunicateGroup` + auto_parallel ProcessMesh).

TPU-native design (SURVEY §7.0): ONE `jax.sharding.Mesh` carries every
parallelism axis. The reference builds a cartesian rank topology and one NCCL
comm group per axis; here the mesh axes ARE the groups — GSPMD emits the
collectives. Axis order puts `mp` (tensor parallel) innermost so its
collectives ride the fastest ICI links, then sep/sharding/dp, with pp
outermost (pipeline traffic is the thinnest).
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["ProcessMesh", "HybridTopology", "get_mesh", "set_mesh",
           "mesh_context", "build_hybrid_mesh", "AXIS_ORDER", "DCN_AXES",
           "global_device_put"]

# outermost → innermost (DCN-most → ICI-most). The dcn_* axes are the
# explicit data-center-network tier of a multi-slice job: traffic on
# them crosses the process/slice boundary (slow, high-latency DCN),
# everything to their right stays on intra-slice ICI. Only dp and pp
# style parallelism may ride DCN — mp/sep/sharding collectives are
# latency-bound and must stay within a slice.
AXIS_ORDER = ("dcn_pp", "dcn_dp", "pp", "dp", "sharding", "sep", "mp")

# axes whose collectives may legally cross the slice boundary
DCN_AXES = ("dcn_pp", "dcn_dp")

_current_mesh: Optional[Mesh] = None


def set_mesh(mesh) -> None:
    global _current_mesh
    _current_mesh = mesh.jax_mesh if isinstance(mesh, ProcessMesh) else mesh


def get_mesh() -> Optional[Mesh]:
    return _current_mesh


class mesh_context:
    def __init__(self, mesh):
        self._mesh = mesh

    def __enter__(self):
        self._prev = get_mesh()
        set_mesh(self._mesh)
        return self._mesh

    def __exit__(self, *exc):
        # single restore path: `_prev` came from get_mesh() and is already
        # a raw Mesh (or None), so assign it back directly
        global _current_mesh
        _current_mesh = self._prev
        return False


def global_device_put(arr, sharding: NamedSharding):
    """device_put that also works when `sharding` spans devices of OTHER
    processes (multi-host; ref: the fleet path where every rank holds the
    full host value and NCCL broadcast/scatter distributes it — SURVEY
    §3.5, §5.8). Single-process this IS jax.device_put; multi-process,
    each process supplies its addressable shards from the (identical)
    host value via make_array_from_callback. Caller contract: `arr` holds
    the same values on every process (seeded init / seeded data), which
    is the same contract the reference's per-rank parameter init has."""
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    # one host copy up front so each shard extraction below is a
    # zero-copy numpy view, not an eager device gather per shard
    host = np.asarray(arr)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx])


class ProcessMesh:
    """ref: paddle.distributed.ProcessMesh(mesh=[[0,1],[2,3]],
    dim_names=["x","y"]). Wraps jax.sharding.Mesh; process ids index
    jax.devices()."""

    def __init__(self, mesh: Sequence, dim_names: Optional[List[str]] = None,
                 shape=None, process_ids=None):
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        devices = np.asarray(jax.devices(), dtype=object)
        dev_arr = np.empty(arr.shape, dtype=object)
        flat_ids = arr.reshape(-1)
        id_to_dev = {d.id: d for d in jax.devices()}
        dev_arr.reshape(-1)[:] = [id_to_dev[int(i)] for i in flat_ids]
        self.jax_mesh = Mesh(dev_arr, tuple(dim_names))
        self._ids = arr
        self.dim_names = list(dim_names)
        self.shape = list(arr.shape)

    @property
    def process_ids(self):
        return self._ids.reshape(-1).tolist()

    def get_dim_size(self, name: str) -> int:
        return self.shape[self.dim_names.index(name)]

    def __enter__(self):
        self._ctx = mesh_context(self.jax_mesh)
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


def build_hybrid_mesh(dp_degree=1, mp_degree=1, pp_degree=1,
                      sharding_degree=1, sep_degree=1, ep_degree=1,
                      dcn_dp_degree=1, dcn_pp_degree=1,
                      devices=None) -> Mesh:
    """Build the 8-axis hybrid mesh (ref: HybridCommunicateGroup's cartesian
    topology, order [M] knob; ep is the expert-parallel degree PaddleNLP MoE
    derives inside the hybrid topology). Degrees of 1 keep the axis present
    (size 1) so sharding specs are stable across configurations.

    `dcn_dp_degree` / `dcn_pp_degree` are the explicit multi-slice (DCN)
    tier: they sit OUTERMOST so each contiguous device block along them
    is one ICI-connected slice — data/pipeline parallelism crosses the
    process boundary, mp/sep/sharding stay within a slice. When any DCN
    degree exceeds 1 and the devices expose `slice_index`, the blocking
    is validated: every DCN-tier block must live on exactly one slice
    (mixing slices inside a block would silently route mp collectives
    over DCN)."""
    devices = list(devices if devices is not None else jax.devices())
    sizes = collections.OrderedDict(
        dcn_pp=dcn_pp_degree, dcn_dp=dcn_dp_degree,
        pp=pp_degree, dp=dp_degree, sharding=sharding_degree, sep=sep_degree,
        ep=ep_degree, mp=mp_degree)
    total = int(np.prod(list(sizes.values())))
    if total != len(devices):
        raise ValueError(
            f"product of degrees {dict(sizes)} = {total} != device count "
            f"{len(devices)}")
    n_dcn = int(dcn_pp_degree) * int(dcn_dp_degree)
    if n_dcn > 1 and all(
            getattr(d, "slice_index", None) is not None for d in devices):
        per_slice = len(devices) // n_dcn
        for blk in range(n_dcn):
            block = devices[blk * per_slice:(blk + 1) * per_slice]
            slices = {d.slice_index for d in block}
            if len(slices) != 1:
                raise ValueError(
                    f"DCN-tier block {blk} spans slices {sorted(slices)}: "
                    "each dcn_dp/dcn_pp block must be one ICI-connected "
                    "slice (reorder `devices` by slice_index)")
    dev_arr = np.asarray(devices, dtype=object).reshape(
        tuple(sizes.values()))
    return Mesh(dev_arr, tuple(sizes.keys()))


def sanitize_spec(mesh, spec):
    """Drop axis names a spec references that the given mesh doesn't have
    (e.g. a P("ep", ...) expert spec used on a mesh without an ep axis) so
    layer-declared specs stay portable across mesh configurations."""
    from jax.sharding import PartitionSpec
    if spec is None:
        return PartitionSpec()
    if mesh is None:
        # no mesh to check against: pass the spec through unchanged so
        # single-device paths keep the layer's declared intent
        return spec
    names = set(mesh.axis_names)
    entries = []
    for e in spec:
        if e is None:
            entries.append(None)
        elif isinstance(e, (tuple, list)):
            kept = tuple(a for a in e if a in names)
            entries.append(kept if kept else None)
        else:
            entries.append(e if e in names else None)
    return PartitionSpec(*entries)


class HybridTopology:
    """ref: fleet/base/topology.py HybridCommunicateGroup — rank/axis
    bookkeeping over the hybrid mesh (degenerates cleanly on 1 host)."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def get_model_parallel_world_size(self) -> int:
        return self.mesh.shape.get("mp", 1)

    def get_data_parallel_world_size(self) -> int:
        return self.mesh.shape.get("dp", 1)

    def get_pipe_parallel_world_size(self) -> int:
        return self.mesh.shape.get("pp", 1)

    def get_sharding_parallel_world_size(self) -> int:
        return self.mesh.shape.get("sharding", 1)

    def get_dcn_data_parallel_world_size(self) -> int:
        return self.mesh.shape.get("dcn_dp", 1)

    def get_dcn_pipe_parallel_world_size(self) -> int:
        return self.mesh.shape.get("dcn_pp", 1)

    def slice_count(self) -> int:
        """Number of ICI-connected slices the mesh spans (the DCN-tier
        block count; 1 on a single-slice job)."""
        return self.get_dcn_data_parallel_world_size() \
            * self.get_dcn_pipe_parallel_world_size()

    def axis_size(self, name: str) -> int:
        return self.mesh.shape.get(name, 1)
