"""Transform family + TransformedDistribution + ExponentialFamily +
LKJCholesky (ref: python/paddle/distribution/{transform,
transformed_distribution,exponential_family,lkj_cholesky}.py — the tail of
SURVEY §2.2 "distributions + transforms + KL").

Oracles: torch.distributions (CPU) for transforms/LKJ, closed forms for
entropy identities.
"""

import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.distribution as D


def _t(x):
    return paddle.to_tensor(np.asarray(x, np.float32))


class TestTransforms:
    def test_affine_roundtrip_and_ldj(self):
        t = D.AffineTransform(loc=1.0, scale=-2.5)
        x = np.linspace(-2, 2, 9).astype(np.float32)
        y = t.forward(_t(x)).numpy()
        np.testing.assert_allclose(y, 1.0 - 2.5 * x, rtol=1e-6)
        np.testing.assert_allclose(t.inverse(_t(y)).numpy(), x, rtol=1e-5)
        ot = torch.distributions.transforms.AffineTransform(1.0, -2.5)
        np.testing.assert_allclose(
            t.forward_log_det_jacobian(_t(x)).numpy(),
            ot.log_abs_det_jacobian(torch.tensor(x),
                                    ot(torch.tensor(x))).numpy(),
            rtol=1e-5)

    @pytest.mark.parametrize("name,ours,theirs", [
        ("exp", D.ExpTransform(),
         torch.distributions.transforms.ExpTransform()),
        ("sigmoid", D.SigmoidTransform(),
         torch.distributions.transforms.SigmoidTransform()),
        ("tanh", D.TanhTransform(),
         torch.distributions.transforms.TanhTransform()),
    ])
    def test_scalar_bijectors_vs_torch(self, name, ours, theirs):
        x = np.linspace(-1.5, 1.5, 11).astype(np.float32)
        tx = torch.tensor(x)
        np.testing.assert_allclose(ours.forward(_t(x)).numpy(),
                                   theirs(tx).numpy(), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            ours.forward_log_det_jacobian(_t(x)).numpy(),
            theirs.log_abs_det_jacobian(tx, theirs(tx)).numpy(),
            rtol=1e-5, atol=1e-6)
        y = ours.forward(_t(x)).numpy()
        np.testing.assert_allclose(ours.inverse(_t(y)).numpy(), x,
                                   rtol=1e-4, atol=1e-5)

    def test_power_and_abs(self):
        x = np.array([0.5, 1.0, 2.0], np.float32)
        p = D.PowerTransform(3.0)
        np.testing.assert_allclose(p.forward(_t(x)).numpy(), x ** 3,
                                   rtol=1e-6)
        np.testing.assert_allclose(p.inverse(_t(x ** 3)).numpy(), x,
                                   rtol=1e-5)
        np.testing.assert_allclose(
            p.forward_log_det_jacobian(_t(x)).numpy(),
            np.log(3 * x ** 2), rtol=1e-5)
        a = D.AbsTransform()
        np.testing.assert_allclose(
            a.forward(_t([-2.0, 3.0])).numpy(), [2.0, 3.0])

    def test_stickbreaking_vs_torch(self):
        t = D.StickBreakingTransform()
        ot = torch.distributions.transforms.StickBreakingTransform()
        x = np.array([[0.3, -0.8, 1.2], [0.0, 0.0, 0.0]], np.float32)
        tx = torch.tensor(x)
        y_ref = ot(tx).numpy()
        y = t.forward(_t(x)).numpy()
        np.testing.assert_allclose(y, y_ref, rtol=1e-5, atol=1e-6)
        assert y.shape == (2, 4)
        np.testing.assert_allclose(np.sum(y, -1), 1.0, rtol=1e-5)
        np.testing.assert_allclose(t.inverse(_t(y)).numpy(), x,
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            t.forward_log_det_jacobian(_t(x)).numpy(),
            ot.log_abs_det_jacobian(tx, ot(tx)).numpy(),
            rtol=1e-4, atol=1e-5)
        assert t.forward_shape((2, 3)) == (2, 4)
        assert t.inverse_shape((2, 4)) == (2, 3)

    def test_softmax_reshape_stack_independent_chain(self):
        sm = D.SoftmaxTransform()
        x = np.array([[0.5, 1.0, -1.0]], np.float32)
        y = sm.forward(_t(x)).numpy()
        np.testing.assert_allclose(np.sum(y, -1), 1.0, rtol=1e-6)
        x2 = sm.inverse(_t(y)).numpy()
        np.testing.assert_allclose(
            sm.forward(_t(x2)).numpy(), y, rtol=1e-5)

        rt = D.ReshapeTransform((2, 3), (6,))
        z = np.arange(6, dtype=np.float32).reshape(1, 2, 3)
        assert rt.forward(_t(z)).shape == [1, 6]
        assert rt.inverse(rt.forward(_t(z))).shape == [1, 2, 3]
        assert rt.forward_shape((5, 2, 3)) == (5, 6)

        st = D.StackTransform([D.ExpTransform(), D.AffineTransform(0., 2.)],
                              axis=-1)
        v = np.array([[0.5, 1.5]], np.float32)
        out = st.forward(_t(v)).numpy()
        np.testing.assert_allclose(out[:, 0], np.exp(0.5), rtol=1e-6)
        np.testing.assert_allclose(out[:, 1], 3.0, rtol=1e-6)

        it = D.IndependentTransform(D.ExpTransform(), 1)
        w = np.ones((2, 3), np.float32)
        ldj = it.forward_log_det_jacobian(_t(w)).numpy()
        assert ldj.shape == (2,)
        np.testing.assert_allclose(ldj, 3.0, rtol=1e-6)

        ch = D.ChainTransform([D.AffineTransform(0.0, 2.0),
                               D.ExpTransform()])
        u = np.array([0.1, 0.7], np.float32)
        np.testing.assert_allclose(ch.forward(_t(u)).numpy(),
                                   np.exp(2 * u), rtol=1e-6)
        np.testing.assert_allclose(
            ch.forward_log_det_jacobian(_t(u)).numpy(),
            np.log(2.0) + 2 * u, rtol=1e-5)
        np.testing.assert_allclose(ch.inverse(_t(np.exp(2 * u))).numpy(), u,
                                   rtol=1e-5)


class TestTransformedDistribution:
    def test_lognormal_via_exp_transform(self):
        td = D.TransformedDistribution(D.Normal(0.3, 0.8),
                                       [D.ExpTransform()])
        ref = D.LogNormal(0.3, 0.8)
        v = np.array([0.5, 1.0, 2.5], np.float32)
        np.testing.assert_allclose(td.log_prob(_t(v)).numpy(),
                                   ref.log_prob(_t(v)).numpy(),
                                   rtol=1e-5)
        paddle.seed(7)
        s = td.sample([2000]).numpy()
        assert s.shape == (2000,)
        assert np.all(s > 0)

    def test_affine_of_normal_matches_normal(self):
        td = D.TransformedDistribution(
            D.Normal(0.0, 1.0), [D.AffineTransform(1.5, 2.0)])
        ref = D.Normal(1.5, 2.0)
        v = np.linspace(-3, 5, 9).astype(np.float32)
        np.testing.assert_allclose(td.log_prob(_t(v)).numpy(),
                                   ref.log_prob(_t(v)).numpy(), rtol=1e-5)

    def test_event_dims_with_stickbreaking(self):
        base = D.Independent(D.Normal(np.zeros(3, np.float32),
                                      np.ones(3, np.float32)), 1)
        td = D.TransformedDistribution(base, [D.StickBreakingTransform()])
        assert td.event_shape == (4,)
        tb = torch.distributions.TransformedDistribution(
            torch.distributions.Independent(
                torch.distributions.Normal(torch.zeros(3), torch.ones(3)),
                1),
            [torch.distributions.transforms.StickBreakingTransform()])
        x = np.array([0.2, -0.4, 0.9], np.float32)
        y = D.StickBreakingTransform().forward(_t(x)).numpy()
        np.testing.assert_allclose(
            td.log_prob(_t(y)).numpy(),
            tb.log_prob(torch.tensor(y)).numpy(), rtol=1e-4, atol=1e-4)


class TestExponentialFamily:
    def test_bregman_entropy_matches_closed_form(self):
        # Normal as an exponential family: θ=(μ/σ², −1/(2σ²)),
        # A = −θ1²/(4θ2) − ½log(−2θ2); carrier E[log h] = −½log(2π)
        import jax.numpy as jnp

        class NormalEF(D.ExponentialFamily):
            def __init__(self, loc, scale):
                self.loc = jnp.float32(loc)
                self.scale = jnp.float32(scale)
                super().__init__(())

            @property
            def _natural_parameters(self):
                return (self.loc / self.scale ** 2,
                        -0.5 / self.scale ** 2)

            def _log_normalizer(self, t1, t2):
                return -t1 ** 2 / (4 * t2) - 0.5 * jnp.log(-2.0 * t2)

            @property
            def _mean_carrier_measure(self):
                return -0.5 * np.log(2 * np.pi)

        for loc, scale in [(0.0, 1.0), (1.3, 0.4), (-2.0, 3.0)]:
            ef = NormalEF(loc, scale)
            ref = float(D.Normal(loc, scale).entropy().numpy())
            np.testing.assert_allclose(float(ef.entropy().numpy()), ref,
                                       rtol=1e-4)


class TestLKJCholesky:
    def test_log_prob_vs_torch(self):
        for dim, conc in [(2, 1.0), (3, 0.5), (4, 2.5)]:
            ours = D.LKJCholesky(dim, conc)
            theirs = torch.distributions.LKJCholesky(dim, conc)
            L = theirs.sample()  # valid cholesky factor from the oracle
            np.testing.assert_allclose(
                float(ours.log_prob(_t(L.numpy())).numpy()),
                float(theirs.log_prob(L)), rtol=1e-4, atol=1e-4)

    def test_sample_is_correlation_cholesky(self):
        paddle.seed(0)
        d = D.LKJCholesky(4, 1.5)
        L = d.sample([64]).numpy()
        assert L.shape == (64, 4, 4)
        # lower triangular
        assert np.allclose(np.triu(L, 1), 0.0, atol=1e-6)
        corr = L @ np.swapaxes(L, -1, -2)
        # unit diagonal, entries in [-1, 1], PSD by construction
        diag = np.diagonal(corr, axis1=-2, axis2=-1)
        np.testing.assert_allclose(diag, 1.0, rtol=1e-4, atol=1e-4)
        assert np.all(np.abs(corr) <= 1.0 + 1e-5)

    def test_batched_concentration(self):
        paddle.seed(1)
        d = D.LKJCholesky(3, np.array([0.8, 2.0], np.float32))
        s = d.sample([5]).numpy()
        assert s.shape == (5, 2, 3, 3)
        lp = d.log_prob(_t(s[0])).numpy()
        assert lp.shape == (2,)


class TestReviewRegressions:
    def test_chain_ldj_tracks_rank_changes(self):
        # reshape (6,)→(2,3) then exp: ldj must be the SCALAR sum over the
        # full event, not a shape-(2,) partial sum
        ch = D.ChainTransform([D.ReshapeTransform((6,), (2, 3)),
                               D.ExpTransform()])
        x = np.arange(6, dtype=np.float32)
        assert ch.event_rank_in == 1 and ch.event_rank_out == 2
        ldj = ch.forward_log_det_jacobian(_t(x)).numpy()
        assert ldj.shape == ()
        np.testing.assert_allclose(float(ldj), x.sum(), rtol=1e-6)
        ildj = ch.inverse_log_det_jacobian(ch.forward(_t(x))).numpy()
        np.testing.assert_allclose(float(ildj), -x.sum(), rtol=1e-5)

    def test_exponential_family_vector_natural_params(self):
        # unit-variance Gaussian vector as an exp family with θ ∈ R^3:
        # A(θ) = Σ θ²/2, E[log h] = -3/2·log(2π) - E[x²]/2 ... use the
        # standard form: entropy must reduce event dims to batch shape
        import jax.numpy as jnp

        class VecNormalEF(D.ExponentialFamily):
            def __init__(self, theta):
                self.theta = jnp.asarray(theta, jnp.float32)
                super().__init__(())

            @property
            def _natural_parameters(self):
                return (self.theta,)

            def _log_normalizer(self, t):
                return jnp.sum(t ** 2) / 2.0

            @property
            def _mean_carrier_measure(self):
                # log h(x) = -x²/2 - ½log 2π per dim; E[x²] = 1 + μ²,
                # μ = θ for unit variance
                d = self.theta.shape[-1]
                return float(-0.5 * np.sum(1.0 + np.asarray(self.theta) ** 2)
                             - 0.5 * d * np.log(2 * np.pi))

        ef = VecNormalEF([0.5, -1.0, 2.0])
        ent = ef.entropy().numpy()
        assert ent.shape == ()
        # independent unit normals: entropy = d/2·log(2πe), location-free
        np.testing.assert_allclose(float(ent),
                                   1.5 * np.log(2 * np.pi * np.e),
                                   rtol=1e-5)
