"""paddle.static parity (ref: python/paddle/static/ — SURVEY §2.2 static
API row).

TPU-native rework (SURVEY §7.0): the reference's static graph is a
ProgramDesc executed by StandaloneExecutor; here a `Program` CAPTURES a
traced jax function (the jaxpr/StableHLO IS the program — SURVEY §1 "static
= traced program under jit"). The user-facing workflow keeps parity:

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 8], "float32")
        y = paddle.nn.Linear(8, 2)(x)        # traced lazily at run()
    exe = static.Executor()
    out, = exe.run(main, feed={"x": arr}, fetch_list=[y])

Ops execute eagerly during `with program_guard` (define-by-run), and the
Program records the (fn, feeds, fetches) closure; Executor.run re-traces
under jax.jit keyed by feed shapes — the compiled executable is cached the
way _ExecutorCache caches StandaloneExecutor instances (§3.3).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core import autograd as _ag

__all__ = ["Program", "program_guard", "default_main_program",
           "default_startup_program", "data", "Executor", "InputSpec",
           "cpu_places", "cuda_places", "device_guard", "name_scope",
           "save_inference_model", "load_inference_model", "nn"]


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(-1 if s is None else s for s in shape)
        self.dtype = dtype
        self.name = name


class _Placeholder(Tensor):
    """A feedable variable: created by static.data; holds zeros until fed."""

    def __init__(self, name, shape, dtype):
        concrete = tuple(1 if (s is None or s < 0) else s for s in shape)
        super().__init__(jnp.zeros(concrete, dtype))
        self._feed_name = name
        self._declared_shape = tuple(
            -1 if (s is None or s < 0) else s for s in shape)


class _OpRecord:
    __slots__ = ("name", "fn", "in_ids", "in_refs", "in_consts", "out_ids")

    def __init__(self, name, fn, in_ids, in_refs, in_consts, out_ids):
        self.name = name
        self.fn = fn
        self.in_ids = in_ids        # per input: id(Tensor) or None
        self.in_refs = in_refs      # weakrefs to live input Tensors (params!)
        self.in_consts = in_consts  # per input: captured array (fallback)
        self.out_ids = out_ids


class Program:
    """Placeholders + the recorded op list built under its guard (the
    Instruction-list analog of §3.3; replay = ProgramInterpreter)."""

    _counter = 0

    def __init__(self):
        Program._counter += 1
        self.id = Program._counter
        self.placeholders: Dict[str, _Placeholder] = {}
        self.ops: List[_OpRecord] = []
        self.random_seed = 0

    # dispatch hook target
    def _record(self, name, fn, tlist, arrs, results):
        import weakref
        in_ids = [id(t) if t is not None else None for t in tlist]
        in_refs = [weakref.ref(t) if t is not None else None for t in tlist]
        self.ops.append(_OpRecord(
            name, fn, in_ids, in_refs, list(arrs), [id(r) for r in results]))

    def replay(self, feed: Dict[str, object]):
        """Re-execute the op list with placeholder values swapped in.
        Returns env mapping recorded-tensor id -> new array."""
        env: Dict[int, object] = {}
        for nm, ph in self.placeholders.items():
            if nm in feed:
                # jnp.asarray alone: feed values may be traced (the
                # save_inference_model export traces through replay)
                env[id(ph)] = jnp.asarray(feed[nm])
        for op in self.ops:
            ins = []
            for tid, ref, const in zip(op.in_ids, op.in_refs, op.in_consts):
                if tid is not None and tid in env:
                    ins.append(env[tid])
                elif ref is not None and ref() is not None:
                    ins.append(ref()._data)  # live tensor (e.g. a parameter)
                else:
                    ins.append(const)
            out = op.fn(*ins)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for oid, o in zip(op.out_ids, outs):
                env[oid] = o
        return env

    def clone(self, for_test: bool = False) -> "Program":
        return self

    def __repr__(self):
        return (f"Program(id={self.id}, feeds={list(self.placeholders)}, "
                f"ops={len(self.ops)})")


_tls = threading.local()


def _current_program() -> Optional[Program]:
    return getattr(_tls, "program", None)


class program_guard:
    def __init__(self, main_program: Program, startup_program: Program = None):
        self.main = main_program

    def __enter__(self):
        from ..core import dispatch as _dispatch
        self._prev = _current_program()
        _tls.program = self.main
        self._prev_rec = _dispatch._static_recorder
        _dispatch.set_static_recorder(self.main._record)
        return self.main

    def __exit__(self, *exc):
        from ..core import dispatch as _dispatch
        _tls.program = self._prev
        _dispatch.set_static_recorder(self._prev_rec)
        return False


_default_main = Program()
_default_startup = Program()


def default_main_program() -> Program:
    return _current_program() or _default_main


def default_startup_program() -> Program:
    return _default_startup


def data(name: str, shape, dtype="float32", lod_level=0) -> _Placeholder:
    """ref: paddle.static.data — declares a feedable graph input."""
    ph = _Placeholder(name, shape, dtype)
    prog = default_main_program()
    prog.placeholders[name] = ph
    return ph


class Executor:
    """ref: paddle.static.Executor — run(program, feed, fetch_list).

    The first run() with a given feed-shape signature traces the fetch
    graph; repeats hit the jit cache (parity: _ExecutorCache →
    StandaloneExecutor build-once)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None, feed: Dict = None,
            fetch_list: Sequence = None, return_numpy: bool = True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        env = program.replay(feed)
        outs = []
        for f in fetch_list:
            if isinstance(f, Tensor):
                a = env.get(id(f), f._data)
            else:
                a = jnp.asarray(f)
            outs.append(np.asarray(a) if return_numpy else a)
        return outs


def cpu_places(device_count=None):
    return ["cpu"]


def cuda_places(device_ids=None):
    import jax as _j
    return [str(d) for d in _j.devices()]


class device_guard:
    def __init__(self, device=None):
        self.device = device

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class name_scope:
    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor,
                         program=None):
    """ref: paddle.static.save_inference_model (python/paddle/static/io.py).
    Serializes the captured Program as a jax.export artifact (weights
    baked in — the same .jaxexport servable jit.save produces) plus a
    .meta.json with the feed names/specs, so ported reference deployment
    code works unchanged:

        save_inference_model(prefix, [x], [out], exe)
        prog, feeds, fetches = load_inference_model(prefix, exe)
        out, = exe.run(prog, feed={feeds[0]: arr}, fetch_list=fetches)
    """
    import json

    program = program or default_main_program()
    feed_vars = list(feed_vars)
    fetch_vars = list(fetch_vars)
    names = [v._feed_name if isinstance(v, _Placeholder) else str(v)
             for v in feed_vars]

    def infer(*arrays):
        env = program.replay(dict(zip(names, arrays)))
        outs = []
        for f in fetch_vars:
            outs.append(env.get(id(f), f._data if isinstance(f, Tensor)
                                else jnp.asarray(f)))
        return tuple(outs)

    import jax as _jax
    from jax import export as jexport
    specs = []
    for i, v in enumerate(feed_vars):
        dims = [int(d) for d in getattr(v, "_declared_shape", v.shape)]
        if any(d < 0 for d in dims):
            # -1 dims (the reference's variable batch) become export
            # symbolic dimensions
            sym = jexport.symbolic_shape(", ".join(
                f"d{i}_{j}" if d < 0 else str(d)
                for j, d in enumerate(dims)))
            specs.append(_jax.ShapeDtypeStruct(sym, v.dtype))
        else:
            specs.append(_jax.ShapeDtypeStruct(tuple(dims), v.dtype))
    exported = jexport.export(_jax.jit(infer))(*specs)
    with open(path_prefix + ".jaxexport", "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + ".meta.json", "w") as f:
        json.dump({"feed_names": names,
                   "feed_shapes": [
                       [int(d) for d in getattr(v, "_declared_shape",
                                                v.shape)]
                       for v in feed_vars],
                   "feed_dtypes": [str(np.dtype(s.dtype)) for s in specs],
                   "n_fetch": len(fetch_vars)}, f)


class _LoadedProgram(Program):
    """Program stand-in returned by load_inference_model: replay calls
    the deserialized exported program instead of an op list."""

    def __init__(self, exported, meta):
        super().__init__()
        self._exported = exported
        # jit once here — a fresh wrapper per replay() would recompile
        # the loaded program on every Executor.run
        self._call = jax.jit(exported.call)
        self._meta = meta
        self.fetch_targets = [Tensor(jnp.zeros(()))
                              for _ in range(meta["n_fetch"])]
        for nm, shp, dt in zip(meta["feed_names"], meta["feed_shapes"],
                               meta["feed_dtypes"]):
            self.placeholders[nm] = _Placeholder(nm, shp, dt)

    def replay(self, feed: Dict[str, object]):
        args = [jnp.asarray(feed[nm]) for nm in self._meta["feed_names"]]
        outs = self._call(*args)
        return {id(t): o for t, o in zip(self.fetch_targets, outs)}


def load_inference_model(path_prefix: str, executor):
    """ref: paddle.static.load_inference_model — returns
    [program, feed_target_names, fetch_targets] runnable via
    Executor.run exactly like the reference."""
    import json

    from ..jit import _deserialize_exported
    exported = _deserialize_exported(path_prefix + ".jaxexport")
    with open(path_prefix + ".meta.json") as f:
        meta = json.load(f)
    prog = _LoadedProgram(exported, meta)
    return [prog, list(meta["feed_names"]), list(prog.fetch_targets)]


class _StaticNN:
    """paddle.static.nn.* façade: the layer zoo doubles as the static op
    set (define-by-run capture)."""

    def __getattr__(self, name):
        from .. import nn as _nn
        fnmap = {"fc": self._fc, "conv2d": self._conv2d,
                 "batch_norm": self._batch_norm}
        if name in fnmap:
            return fnmap[name]
        # control-flow capture ops (ref: python/paddle/static/nn/control_flow.py)
        from . import control_flow as _cf
        if name in _cf.__all__ or name == "control_flow":
            return _cf if name == "control_flow" else getattr(_cf, name)
        raise AttributeError(name)

    @staticmethod
    def _fc(x, size, num_flatten_dims=1, activation=None, name=None):
        from .. import nn as _nn
        from ..nn import functional as F
        l = _nn.Linear(int(x.shape[-1]), size)
        out = l(x)
        if activation == "relu":
            out = F.relu(out)
        elif activation == "softmax":
            out = F.softmax(out)
        return out

    @staticmethod
    def _conv2d(input, num_filters, filter_size, stride=1, padding=0,
                act=None, name=None):
        from .. import nn as _nn
        from ..nn import functional as F
        l = _nn.Conv2D(int(input.shape[1]), num_filters, filter_size,
                       stride=stride, padding=padding)
        out = l(input)
        if act == "relu":
            out = F.relu(out)
        return out

    @staticmethod
    def _batch_norm(input, act=None, name=None):
        from .. import nn as _nn
        from ..nn import functional as F
        l = _nn.BatchNorm2D(int(input.shape[1]))
        out = l(input)
        if act == "relu":
            out = F.relu(out)
        return out


nn = _StaticNN()
