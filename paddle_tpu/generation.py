"""Text generation (ref capability: PaddleNLP GenerationMixin —
model.generate with greedy_search / sampling decode strategies,
paddlenlp/generation/utils.py).

TPU-first mechanism: autoregressive decoding runs the model on a FIXED
[B, prompt+max_new_tokens] buffer every step and reads the logits at the
current position. Causal attention makes positions > t irrelevant to the
step-t logits, so the pad tail is harmless — and the constant shape means
ONE compiled executable serves every step (no per-length recompiles, the
XLA analog of the reference's static decode graph). The serving-grade
O(1)-per-step path is the paged/masked decode attention kernel set
(ops/paged_attention.py, incubate.nn.functional.masked_multihead_attention)
used by the inference Predictor; this module is the framework-level
`generate()` every CausalLM model family shares.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor
from .core import autograd as ag
from .framework.random import next_key

__all__ = ["generate"]


def _logits_fn(model, ids_arr):
    """One forward on the padded buffer → [B, S, V] raw logits array."""
    out = model(Tensor(ids_arr))
    if isinstance(out, tuple):
        out = out[-1]
    return out._data


def _sample_token(logits, strategy, top_k, top_p, temperature):
    """logits [B, V] → token ids [B]."""
    if strategy == "greedy_search" or (temperature is not None
                                       and temperature <= 0.0):
        # temperature 0 degenerates to greedy (the usual convention),
        # never a silent fall-through to temperature-1 sampling
        return jnp.argmax(logits, -1).astype(jnp.int32)
    if temperature is not None and temperature != 1.0:
        logits = logits / temperature
    if top_k:
        kth = jnp.sort(logits, -1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p and top_p < 1.0:
        sorted_logits = jnp.sort(logits, -1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, -1)
        cum = jnp.cumsum(probs, -1)
        # keep the smallest prefix with cumulative prob >= top_p
        cutoff_idx = jnp.sum(cum < top_p, -1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], -1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(next_key(), logits, -1).astype(jnp.int32)


def generate(model, input_ids, max_new_tokens: int = 20,
             decode_strategy: str = "sampling", top_k: Optional[int] = None,
             top_p: Optional[float] = None, temperature: float = 1.0,
             eos_token_id: Optional[int] = None, pad_token_id: int = 0):
    """ref: PaddleNLP model.generate(...). Returns (generated_ids, scores):
    generated_ids [B, max_new_tokens] holds ONLY the new tokens (prompt
    excluded, PaddleNLP convention), padded with pad_token_id after eos;
    scores [B, max_new_tokens] are the chosen tokens' log-probs.
    """
    if decode_strategy not in ("greedy_search", "sampling"):
        raise ValueError(f"decode_strategy {decode_strategy!r}: expected "
                         "'greedy_search' or 'sampling'")
    ids = input_ids._data if isinstance(input_ids, Tensor) \
        else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    B, S0 = ids.shape
    total = S0 + max_new_tokens
    buf = jnp.concatenate(
        [ids, jnp.full((B, max_new_tokens), pad_token_id, jnp.int32)], 1)
    finished = jnp.zeros((B,), bool)
    out_tokens = []
    out_scores = []
    was_training = getattr(model, "training", False)
    if hasattr(model, "eval"):
        model.eval()
    try:
        with ag.no_grad():
            for t in range(S0 - 1, total - 1):
                logits = _logits_fn(model, buf)[:, t]
                tok = _sample_token(logits, decode_strategy, top_k, top_p,
                                    temperature)
                logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
                score = jnp.take_along_axis(logp, tok[:, None], -1)[:, 0]
                if eos_token_id is not None:
                    tok = jnp.where(finished, pad_token_id, tok)
                    score = jnp.where(finished, 0.0, score)
                    finished = finished | (tok == eos_token_id)
                buf = buf.at[:, t + 1].set(tok)
                out_tokens.append(tok)
                out_scores.append(score)
                if eos_token_id is not None and bool(jnp.all(finished)):
                    break
    finally:
        if was_training and hasattr(model, "train"):
            model.train()
    gen = jnp.stack(out_tokens, 1)
    sc = jnp.stack(out_scores, 1)
    if gen.shape[1] < max_new_tokens:  # early eos: pad to the full width
        padw = max_new_tokens - gen.shape[1]
        gen = jnp.concatenate(
            [gen, jnp.full((B, padw), pad_token_id, jnp.int32)], 1)
        sc = jnp.concatenate([sc, jnp.zeros((B, padw), sc.dtype)], 1)
    return Tensor(gen), Tensor(sc)
