"""paddlelint (paddle_tpu.analysis): per-rule true-positive/negative
fixtures, suppression comments, baseline round-trip, the whole-repo CI
gate, and seeded-defect detection in scratch copies of real modules.

The fixtures are the rule contract: each PTxxx has at least one snippet
the rule MUST flag and one structurally-similar snippet it must NOT flag
(the negative encodes the false-positive class the analyzer was tuned
against — shape branches, split-then-use keys, lock-guarded writes)."""

import json
import os
import shutil
import textwrap

import pytest

from paddle_tpu.analysis import (Config, analyze_paths, analyze_source,
                                 load_baseline, save_baseline,
                                 split_baseline)
from paddle_tpu.analysis.cli import main as lint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(src, **cfg_kw):
    return analyze_source(textwrap.dedent(src), Config(**cfg_kw))


def _rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------- PT001

class TestPT001TracerLeak:
    def test_branch_on_traced_value(self):
        fs = _lint("""
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return x * 2
        """)
        assert _rules(fs) == ["PT001"]
        assert fs[0].severity == "error"
        assert "branch" in fs[0].detail

    def test_host_conversion_of_traced_value(self):
        fs = _lint("""
            import jax

            @jax.jit
            def f(x):
                return float(x) * 2
        """)
        assert _rules(fs) == ["PT001"]
        assert "float" in fs[0].detail

    def test_item_on_traced_value(self):
        fs = _lint("""
            import jax

            @jax.jit
            def f(x):
                y = x + 1
                return y.item()
        """)
        assert _rules(fs) == ["PT001"]

    def test_taint_propagates_through_local_call(self):
        # interprocedural: leak is in a helper only reachable with a
        # traced argument
        fs = _lint("""
            import jax

            def helper(v):
                if v > 0:
                    return v
                return -v

            @jax.jit
            def f(x):
                return helper(x * 2)
        """)
        assert "PT001" in _rules(fs)
        assert any(f.qualname == "helper" for f in fs)

    def test_shape_branch_is_not_a_leak(self):
        # .shape / .ndim / len() are static under trace
        fs = _lint("""
            import jax

            @jax.jit
            def f(x):
                if x.shape[0] > 1 and x.ndim == 2:
                    return x * 2
                return x
        """)
        assert "PT001" not in _rules(fs)

    def test_static_argnums_param_exempt(self):
        fs = _lint("""
            import functools
            import jax

            @functools.partial(jax.jit, static_argnums=(1,))
            def f(x, mode):
                if mode == "fast":
                    return x * 2
                return x
        """)
        assert "PT001" not in _rules(fs)

    def test_isinstance_guard_exempts_name(self):
        fs = _lint("""
            import jax

            @jax.jit
            def f(x, s=None):
                if isinstance(s, int) and s == 0:
                    return x
                return x * 2
        """)
        assert "PT001" not in _rules(fs)


# ---------------------------------------------------------------- PT002

class TestPT002RetraceHazard:
    def test_jit_inside_loop(self):
        fs = _lint("""
            import jax

            def build(fns):
                outs = []
                for fn in fns:
                    outs.append(jax.jit(fn))
                return outs
        """)
        assert _rules(fs) == ["PT002"]
        assert "jit-in-loop" in fs[0].detail

    def test_unhashable_static_argnums(self):
        fs = _lint("""
            import jax

            def build(fn):
                return jax.jit(fn, static_argnums={1, 2})
        """)
        assert _rules(fs) == ["PT002"]
        assert "static-args" in fs[0].detail

    def test_module_level_jit_ok(self):
        fs = _lint("""
            import jax

            def step(x):
                return x * 2

            jitted = jax.jit(step)
        """)
        assert "PT002" not in _rules(fs)

    def test_shape_branch_reported_only_under_strict(self):
        src = """
            import jax

            @jax.jit
            def f(x):
                if x.shape[0] > 1:
                    return x * 2
                return x
        """
        assert "PT002" not in _rules(_lint(src))
        strict = [f for f in _lint(src, strict=True) if f.rule == "PT002"]
        assert strict and strict[0].severity == "info"


# ---------------------------------------------------------------- PT003

class TestPT003HostSync:
    def test_sync_in_hot_entry(self):
        fs = _lint("""
            class Trainer:
                def training_step(self, batch):
                    loss = self.step(batch)
                    return loss.item()
        """)
        assert _rules(fs) == ["PT003"]
        assert "sync" in fs[0].detail

    def test_sync_reachable_from_hot_entry(self):
        fs = _lint("""
            def _log(loss):
                return float(loss.numpy())

            def training_step(batch):
                loss = batch * 2
                return _log(loss)
        """)
        assert "PT003" in _rules(fs)
        assert any(f.qualname == "_log" for f in fs)

    def test_sync_outside_hot_region_ok(self):
        fs = _lint("""
            def summarize(loss):
                return loss.item()

            def unrelated(batch):
                return summarize(batch)
        """)
        assert "PT003" not in _rules(fs)


# ---------------------------------------------------------------- PT004

class TestPT004RngHygiene:
    def test_key_reuse(self):
        fs = _lint("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (2,))
                b = jax.random.uniform(key, (2,))
                return a + b
        """)
        assert _rules(fs) == ["PT004"]
        assert "key-reuse" in fs[0].detail

    def test_split_then_use_ok(self):
        fs = _lint("""
            import jax

            def sample(key):
                key, sub = jax.random.split(key)
                a = jax.random.normal(sub, (2,))
                key, sub = jax.random.split(key)
                b = jax.random.uniform(sub, (2,))
                return a + b
        """)
        assert "PT004" not in _rules(fs)

    def test_host_rng_in_traced_code(self):
        fs = _lint("""
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                noise = np.random.randn(4)
                return x + noise
        """)
        assert "PT004" in _rules(fs)
        assert any("host-rng" in f.detail for f in fs)

    def test_host_rng_outside_trace_ok(self):
        fs = _lint("""
            import numpy as np

            def make_batch(n):
                return np.random.randn(n, 4)
        """)
        assert "PT004" not in _rules(fs)


# ---------------------------------------------------------------- PT005

class TestPT005FlagsAtTraceTime:
    def test_flags_guard_in_traced_function(self):
        fs = _lint("""
            import jax
            from paddle_tpu.flags import flags_guard

            @jax.jit
            def f(x):
                with flags_guard(flash_impl="composite"):
                    return x * 2
        """)
        assert _rules(fs) == ["PT005"]
        assert "flags" in fs[0].detail

    def test_set_flags_in_traced_function(self):
        fs = _lint("""
            import jax
            import paddle_tpu

            @jax.jit
            def f(x):
                paddle_tpu.set_flags({"FLAGS_flash_impl": "intree"})
                return x * 2
        """)
        assert _rules(fs) == ["PT005"]

    def test_flags_outside_trace_ok(self):
        fs = _lint("""
            import paddle_tpu

            def configure():
                paddle_tpu.set_flags({"FLAGS_flash_impl": "intree"})
        """)
        assert "PT005" not in _rules(fs)


# ---------------------------------------------------------------- PT006

class TestPT006SharedState:
    def test_unguarded_global_write_from_thread(self):
        fs = _lint("""
            import threading

            _events = []
            _count = 0

            def _worker():
                global _count
                _count += 1
                _events.append("tick")

            def start():
                threading.Thread(target=_worker, daemon=True).start()
        """)
        assert _rules(fs) == ["PT006"]
        assert {f.detail for f in fs} == {"write:_count", "write:_events"}

    def test_lock_guarded_write_ok(self):
        fs = _lint("""
            import threading

            _lock = threading.Lock()
            _count = 0

            def _worker():
                global _count
                with _lock:
                    _count += 1

            def start():
                threading.Thread(target=_worker, daemon=True).start()
        """)
        assert "PT006" not in _rules(fs)

    def test_local_rebind_ok(self):
        # a local that shadows a module global is not shared state
        fs = _lint("""
            import threading

            _count = 0

            def _worker():
                _count = 1
                return _count

            def start():
                threading.Thread(target=_worker, daemon=True).start()
        """)
        assert "PT006" not in _rules(fs)

    def test_same_write_outside_thread_region_ok(self):
        fs = _lint("""
            _events = []

            def record(e):
                _events.append(e)
        """)
        assert "PT006" not in _rules(fs)

    def test_trace_ring_exporter_unguarded_flagged(self):
        # the observability.tracing background-exporter shape with the
        # lock REMOVED: flush thread drains a module-level ring — PT006
        fs = _lint("""
            import threading

            _ring = []

            def _flush_loop():
                while _ring:
                    _ring.pop()

            def start_exporter():
                threading.Thread(target=_flush_loop,
                                 daemon=True).start()
        """)
        assert "PT006" in _rules(fs)
        assert any(f.detail == "write:_ring" for f in fs)

    def test_trace_ring_exporter_lock_guarded_ok(self):
        # the shipped recorder discipline: every ring access from the
        # flush thread sits under the one module lock
        fs = _lint("""
            import threading

            _lock = threading.Lock()
            _ring = []

            def _flush_loop():
                with _lock:
                    while _ring:
                        _ring.pop()

            def start_exporter():
                threading.Thread(target=_flush_loop,
                                 daemon=True).start()
        """)
        assert "PT006" not in _rules(fs)


# ----------------------------------------------------------- suppression

class TestSuppression:
    LEAKY = """
        import jax

        @jax.jit
        def f(x):
            if x > 0:{comment}
                return x
            return x * 2
    """

    def test_line_suppression(self):
        src = self.LEAKY.format(comment="  # paddlelint: disable=PT001")
        assert _lint(src) == []

    def test_wrong_rule_does_not_suppress(self):
        src = self.LEAKY.format(comment="  # paddlelint: disable=PT003")
        assert _rules(_lint(src)) == ["PT001"]

    def test_file_wide_suppression(self):
        src = ("# paddlelint: disable-file=PT001\n"
               + textwrap.dedent(self.LEAKY.format(comment="")))
        assert analyze_source(src, Config()) == []

    def test_disable_all(self):
        src = self.LEAKY.format(comment="  # paddlelint: disable=all")
        assert _lint(src) == []


# -------------------------------------------------------------- baseline

class TestBaseline:
    def _findings(self):
        return _lint("""
            import jax

            @jax.jit
            def f(x):
                return float(x)
        """)

    def test_round_trip(self, tmp_path):
        fs = self._findings()
        path = str(tmp_path / "baseline.json")
        save_baseline(path, fs, {fs[0].baseline_key: "accepted: legacy"})
        loaded = load_baseline(path)
        assert loaded == {fs[0].baseline_key: "accepted: legacy"}
        fresh, stale = split_baseline(fs, loaded)
        assert fresh == [] and stale == []

    def test_key_is_line_number_free(self):
        a = self._findings()[0]
        b = _lint("""
            import jax

            # shifted down by a comment block: the baseline key must
            # not move with the line number
            @jax.jit
            def f(x):
                return float(x)
        """)[0]
        assert a.line != b.line
        assert a.baseline_key == b.baseline_key

    def test_split_reports_fresh_and_stale(self, tmp_path):
        fs = self._findings()
        fresh, stale = split_baseline(fs, {"PT999|gone.py|f|x": "old"})
        assert [f.rule for f in fresh] == ["PT001"]
        assert stale == ["PT999|gone.py|f|x"]

    def test_missing_justification_stamped(self, tmp_path):
        fs = self._findings()
        path = str(tmp_path / "baseline.json")
        save_baseline(path, fs, {})
        with open(path) as f:
            data = json.load(f)
        assert data["entries"][0]["justification"] == "TODO: justify"


# ------------------------------------------------------------------ CLI

class TestCli:
    def _write(self, tmp_path, src):
        p = tmp_path / "mod.py"
        p.write_text(textwrap.dedent(src))
        return str(p)

    LEAKY = """
        import jax

        @jax.jit
        def f(x):
            return float(x)
    """

    def test_exit_one_on_findings(self, tmp_path, capsys):
        assert lint_main([self._write(tmp_path, self.LEAKY)]) == 1
        out = capsys.readouterr().out
        assert "PT001" in out and "1 finding(s)" in out

    def test_exit_zero_when_clean(self, tmp_path, capsys):
        assert lint_main([self._write(tmp_path, "x = 1\n")]) == 0

    def test_json_output(self, tmp_path, capsys):
        assert lint_main(["--json", self._write(tmp_path, self.LEAKY)]) == 1
        data = json.loads(capsys.readouterr().out)
        assert data["findings"][0]["rule"] == "PT001"
        assert "PT001" in data["rules"]

    def test_baseline_gates_to_zero(self, tmp_path, capsys):
        mod = self._write(tmp_path, self.LEAKY)
        base = str(tmp_path / "base.json")
        assert lint_main([mod, "--baseline", base,
                          "--write-baseline"]) == 0
        capsys.readouterr()
        assert lint_main([mod, "--baseline", base]) == 0
        assert "1 baselined" in capsys.readouterr().out

    def test_stale_baseline_reported(self, tmp_path, capsys):
        mod = self._write(tmp_path, self.LEAKY)
        base = str(tmp_path / "base.json")
        assert lint_main([mod, "--baseline", base,
                          "--write-baseline"]) == 0
        clean = self._write(tmp_path, "x = 1\n")
        capsys.readouterr()
        assert lint_main([clean, "--baseline", base]) == 0
        assert "stale baseline" in capsys.readouterr().out
        assert lint_main([clean, "--baseline", base,
                          "--fail-stale"]) == 1

    def test_rules_subset(self, tmp_path):
        mod = self._write(tmp_path, self.LEAKY)
        assert lint_main(["--rules", "PT006", mod]) == 0
        assert lint_main(["--rules", "PT001", mod]) == 1

    def test_unknown_rule_is_usage_error(self, tmp_path):
        assert lint_main(["--rules", "PT999",
                          self._write(tmp_path, "x = 1\n")]) == 2


# ------------------------------------------------- whole-repo CI gate

class TestRepoGate:
    def test_package_clean_against_baseline(self, capsys):
        """The tier-1 gate: paddlelint over paddle_tpu/ must produce zero
        non-baselined findings (same invocation as tools/paddlelint.py)."""
        rc = lint_main([os.path.join(REPO, "paddle_tpu"), "--baseline",
                        os.path.join(REPO, "tools",
                                     "paddlelint_baseline.json")])
        out = capsys.readouterr().out
        assert rc == 0, f"paddlelint gate failed:\n{out}"
        assert "0 finding(s)" in out

    def test_baseline_entries_are_justified(self):
        base = load_baseline(os.path.join(
            REPO, "tools", "paddlelint_baseline.json"))
        for key, justification in base.items():
            assert justification and "TODO" not in justification, key


# ------------------------------------------- seeded-defect detection

class TestSeededDefects:
    """Acceptance check: the analyzer must catch a tracer leak and an
    unguarded shared-state write seeded into scratch copies of the real
    modules it is meant to police."""

    def _scratch(self, tmp_path, rel, appended):
        dst = tmp_path / os.path.basename(rel)
        shutil.copy(os.path.join(REPO, rel), dst)
        with open(dst, "a") as f:
            f.write(textwrap.dedent(appended))
        return str(dst)

    def test_seeded_tracer_leak_in_trainer(self, tmp_path):
        clean = analyze_paths(
            [self._scratch(tmp_path, "paddle_tpu/trainer/trainer.py", "")])
        seeded = analyze_paths([self._scratch(
            tmp_path, "paddle_tpu/trainer/trainer.py", """

            import jax as _seeded_jax

            @_seeded_jax.jit
            def _seeded_step(loss):
                if loss > 0:
                    return loss
                return float(loss)
            """)])
        new = {f.baseline_key for f in seeded} - {f.baseline_key
                                                  for f in clean}
        hits = [f for f in seeded if f.baseline_key in new
                and f.rule == "PT001" and f.qualname == "_seeded_step"]
        assert len(hits) == 2  # the branch AND the float()

    def test_seeded_unguarded_write_in_watchdog(self, tmp_path):
        clean = analyze_paths([self._scratch(
            tmp_path, "paddle_tpu/distributed/watchdog.py", "")])
        assert not [f for f in clean if f.rule == "PT006"]
        seeded = analyze_paths([self._scratch(
            tmp_path, "paddle_tpu/distributed/watchdog.py", """

            _seeded_flight_log = []

            def _seeded_recorder_loop():
                _seeded_flight_log.append("tick")

            def _seeded_start_recorder():
                threading.Thread(target=_seeded_recorder_loop,
                                 daemon=True).start()
            """)])
        hits = [f for f in seeded if f.rule == "PT006"
                and f.qualname == "_seeded_recorder_loop"]
        assert len(hits) == 1
        assert hits[0].detail == "write:_seeded_flight_log"


# ---------------------------------------------------- kernel rules (PK)

_PALLAS_HEADER = """\
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.oracles import register_oracle


def _ref(*args, **kwargs):
    return args[0]

"""


_CERTIFY = """
register_oracle("run", kernel=run, reference=_ref,
                parity_test="tests/test_oracles.py::TestOracleParity")
"""


def _klint(src, certify=True, **cfg_kw):
    """Pallas fixture: shared header (imports + a dummy reference) plus,
    by default, a register_oracle on `run` so PK105 never pollutes the
    other rules' assertions."""
    body = _PALLAS_HEADER + textwrap.dedent(src)
    if certify:
        body += _CERTIFY
    return analyze_source(body, Config(**cfg_kw))


class TestPK101IndexMapOob:
    def test_unclamped_prefetch_table_read(self):
        fs = _klint("""
            def _kern(tab_ref, x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x, table):
                return pl.pallas_call(
                    _kern,
                    grid_spec=pltpu.PrefetchScalarGridSpec(
                        num_scalar_prefetch=1,
                        grid=(4,),
                        in_specs=[pl.BlockSpec(
                            (1, 128), lambda i, tab: (tab[i], 0))],
                        out_specs=pl.BlockSpec(
                            (1, 128), lambda i, tab: (i, 0)),
                    ),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                )(table, x)
        """)
        assert _rules(fs) == ["PK101"]
        assert fs[0].severity == "error"
        assert fs[0].detail.startswith("oob:in1:")

    def test_clamped_table_read_ok(self):
        fs = _klint("""
            def _kern(tab_ref, x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x, table):
                return pl.pallas_call(
                    _kern,
                    grid_spec=pltpu.PrefetchScalarGridSpec(
                        num_scalar_prefetch=1,
                        grid=(4,),
                        in_specs=[pl.BlockSpec(
                            (1, 128),
                            lambda i, tab: (jnp.clip(tab[i], 0, 7), 0))],
                        out_specs=pl.BlockSpec(
                            (1, 128), lambda i, tab: (i, 0)),
                    ),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                )(table, x)
        """)
        assert fs == []

    def test_literal_negative_block_index(self):
        fs = _klint("""
            def _kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x):
                return pl.pallas_call(
                    _kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((1, 128), lambda i: (-1, 0))],
                    out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                )(x)
        """)
        assert _rules(fs) == ["PK101"]
        assert fs[0].detail.startswith("neg:in0:")


class TestPK102BlockSpecMismatch:
    def test_index_map_return_arity_vs_block_rank(self):
        fs = _klint("""
            def _kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x):
                return pl.pallas_call(
                    _kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((1, 128), lambda i: i)],
                    out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                )(x)
        """)
        assert _rules(fs) == ["PK102"]
        assert "rank:in0:1!=2" == fs[0].detail

    def test_index_map_param_count_vs_grid(self):
        fs = _klint("""
            def _kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x):
                return pl.pallas_call(
                    _kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((1, 128),
                                           lambda i, j: (i, 0))],
                    out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                )(x)
        """)
        assert _rules(fs) == ["PK102"]
        assert "arity:in0:2!=1" == fs[0].detail

    def test_unaligned_lane_dim_is_warning(self):
        fs = _klint("""
            def _kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x):
                return pl.pallas_call(
                    _kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((8, 100), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((8, 100), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                )(x)
        """)
        assert _rules(fs) == ["PK102"]
        assert all(f.severity == "warning" for f in fs)
        assert {f.detail for f in fs} == {"lane:in0:100", "lane:out0:100"}

    def test_kernel_ref_count_vs_operand_list(self):
        fs = _klint("""
            def _kern(x_ref, y_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x):
                return pl.pallas_call(
                    _kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((1, 128), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                )(x)
        """)
        assert _rules(fs) == ["PK102"]
        assert fs[0].detail == "refs:3!=2"


class TestPK103AliasHazards:
    def test_alias_index_out_of_range(self):
        fs = _klint("""
            def _kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x):
                return pl.pallas_call(
                    _kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((1, 128), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    input_output_aliases={5: 0},
                )(x)
        """)
        assert _rules(fs) == ["PK103"]
        assert fs[0].detail == "alias-range:5:0"

    def test_widened_alias_dtype(self):
        fs = _klint("""
            def _kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x):
                return pl.pallas_call(
                    _kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((1, 128), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct(x.shape, jnp.float32),
                    input_output_aliases={0: 0},
                )(x)
        """)
        assert _rules(fs) == ["PK103"]
        assert fs[0].detail.startswith("alias-dtype:0:0:")

    def test_matching_alias_pair_ok(self):
        fs = _klint("""
            def _kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x):
                return pl.pallas_call(
                    _kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((1, 128), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    input_output_aliases={0: 0},
                )(x)
        """)
        assert fs == []

    def test_aliased_pair_with_different_specs(self):
        fs = _klint("""
            def _kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def run(x):
                return pl.pallas_call(
                    _kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((2, 128), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    input_output_aliases={0: 0},
                )(x)
        """)
        assert _rules(fs) == ["PK103"]
        assert fs[0].detail == "alias-spec:0:0"

    RAW = """
        def _kern(pg_ref, xin_ref, o_ref):
{body}

        def run(x, pg):
            def page_map(i, pg):
                return (jnp.clip(pg[i], 0, 7), 0)
            spec = pl.BlockSpec((1, 128), page_map)
            return pl.pallas_call(
                _kern,
                grid_spec=pltpu.PrefetchScalarGridSpec(
                    num_scalar_prefetch=1,
                    grid=(4,),
                    in_specs=[spec],
                    out_specs=spec,
                ),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                input_output_aliases={{1: 0}},
                compiler_params=pltpu.CompilerParams(
                    dimension_semantics=("arbitrary",)),
            )(pg, x)
    """

    def test_unguarded_aliased_read_with_revisiting_map(self):
        fs = _klint(self.RAW.format(
            body="            o_ref[:] = xin_ref[:] * 2"))
        assert _rules(fs) == ["PK103"]
        assert fs[0].detail.startswith("alias-raw:xin_ref:")

    def test_seed_on_first_visit_pattern_ok(self):
        fs = _klint(self.RAW.format(body=(
            "            @pl.when(pl.program_id(0) == 0)\n"
            "            def _seed():\n"
            "                o_ref[:] = xin_ref[:]")))
        assert fs == []


class TestPK104SubF32Accumulator:
    MATMUL = """
        def _kern(x_ref, o_ref, acc_ref):
            acc_ref[:] = jax.lax.dot(x_ref[:], x_ref[:]{pet})
            o_ref[:] = acc_ref[:].astype(o_ref.dtype)

        def run(x):
            return pl.pallas_call(
                _kern,
                grid=(4,),
                in_specs=[pl.BlockSpec((128, 128), lambda i: (0, 0))],
                out_specs=pl.BlockSpec((128, 128), lambda i: (0, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                scratch_shapes=[pltpu.VMEM((128, 128), {acc})],
                compiler_params=pltpu.CompilerParams(
                    dimension_semantics=("arbitrary",)),
            )(x)
    """

    def test_bf16_scratch_accumulator(self):
        fs = _klint(self.MATMUL.format(
            pet="", acc="jnp.bfloat16"))
        assert _rules(fs) == ["PK104"]
        assert fs[0].detail.startswith("acc:")

    def test_f32_scratch_ok(self):
        fs = _klint(self.MATMUL.format(
            pet="", acc="jnp.float32"))
        assert fs == []

    def test_sub_f32_preferred_element_type(self):
        fs = _klint(self.MATMUL.format(
            pet=",\n                preferred_element_type=jnp.bfloat16",
            acc="jnp.float32"))
        assert _rules(fs) == ["PK104"]
        assert fs[0].detail.startswith("pet:")

    def test_gate_requires_matmul_or_softmax(self):
        # bf16 scratch in a pure data-movement kernel: not an accumulator
        fs = _klint("""
            def _kern(x_ref, o_ref, tmp_ref):
                tmp_ref[:] = x_ref[:]
                o_ref[:] = tmp_ref[:]

            def run(x):
                return pl.pallas_call(
                    _kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((128, 128),
                                           lambda i: (0, 0))],
                    out_specs=pl.BlockSpec((128, 128),
                                           lambda i: (0, 0)),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                    scratch_shapes=[pltpu.VMEM((128, 128),
                                               jnp.bfloat16)],
                    compiler_params=pltpu.CompilerParams(
                        dimension_semantics=("arbitrary",)),
                )(x)
        """)
        assert fs == []


class TestPK105OracleCertification:
    UNIT = """
        def _kern(x_ref, o_ref):
            o_ref[:] = x_ref[:]

        def run(x):
            return pl.pallas_call(
                _kern,
                grid=(4,),
                in_specs=[pl.BlockSpec((1, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(x)
    """

    def test_uncertified_kernel_flagged(self):
        fs = _klint(self.UNIT, certify=False)
        assert _rules(fs) == ["PK105"]
        assert fs[0].detail == "oracle:run"
        assert fs[0].severity == "warning"

    def test_registration_certifies(self):
        assert _klint(self.UNIT) == []

    def test_certification_reaches_through_wrappers(self):
        # register the public entry; the pallas_call lives two call
        # edges down — the closure must cover it
        fs = _klint("""
            def _kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def _impl(x):
                return pl.pallas_call(
                    _kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((1, 128), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                )(x)

            def _dispatch(x):
                return _impl(x)

            def run(x):
                return _dispatch(x)
        """)
        assert fs == []

    def test_certification_follows_defvjp(self):
        # custom_vjp: the kernel call sits in the fwd rule, only the
        # public primal is registered — defvjp linkage must cover it
        fs = _klint("""
            def _kern(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def _fwd(x):
                y = pl.pallas_call(
                    _kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((1, 128), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                )(x)
                return y, x

            def _bwd(res, g):
                return (g,)

            @jax.custom_vjp
            def run(x):
                return _fwd(x)[0]

            run.defvjp(_fwd, _bwd)
        """)
        assert fs == []


class TestKernelResolutionThroughIndirection:
    """The callgraph fix this PR rides on: kernels reached through
    functools.partial locals and factory-returned closures must resolve
    to their FunctionInfo so the PK checks see real params."""

    # indented to match the 12-space method-level fragments it is
    # concatenated onto (dedent runs on the joined string)
    CALL = """
            def run(x):
                {bind}
                return pl.pallas_call(
                    kern,
                    grid=(4,),
                    in_specs=[pl.BlockSpec((1, 128), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                )(x)
    """

    def test_partial_bound_kwargs_subtracted(self):
        fs = _klint("""
            def _kern(x_ref, o_ref, *, eps):
                o_ref[:] = x_ref[:] + eps
        """ + self.CALL.format(
            bind="kern = functools.partial(_kern, eps=1e-6)"))
        assert fs == []

    def test_bad_refs_detected_through_partial(self):
        fs = _klint("""
            def _kern(x_ref, y_ref, o_ref, *, eps):
                o_ref[:] = x_ref[:] + eps
        """ + self.CALL.format(
            bind="kern = functools.partial(_kern, eps=1e-6)"))
        assert _rules(fs) == ["PK102"]
        assert fs[0].detail == "refs:3!=2"

    def test_bad_refs_detected_through_factory_closure(self):
        fs = _klint("""
            def make_kernel(eps):
                def _kern(x_ref, y_ref, o_ref):
                    o_ref[:] = x_ref[:] + eps
                return _kern
        """ + self.CALL.format(
            bind="kern = make_kernel(0.5)"))
        assert _rules(fs) == ["PK102"]
        assert fs[0].detail == "refs:3!=2"


# ------------------------------------------------ collective rule (PC)

class TestPC201BranchDivergentCollective:
    def test_psum_under_python_branch_in_shard_map_body(self):
        fs = _lint("""
            import jax
            from jax.experimental.shard_map import shard_map

            def _body(x):
                if x.shape[0] > 128:
                    x = jax.lax.psum(x, "dp")
                return x

            def run(mesh, x):
                f = shard_map(_body, mesh=mesh, in_specs=None,
                              out_specs=None)
                return f(x)
        """)
        assert _rules(fs) == ["PC201"]
        assert fs[0].severity == "error"
        assert fs[0].qualname == "_body"
        assert fs[0].detail.startswith("branch-collective:psum:")

    def test_collective_in_cond_branch_fn(self):
        fs = _lint("""
            import jax
            from jax.experimental.shard_map import shard_map

            def _yes(x):
                return jax.lax.psum(x, "dp")

            def _no(x):
                return x

            def _body(flag, x):
                return jax.lax.cond(flag, _yes, _no, x)

            def run(mesh, flag, x):
                return shard_map(_body, mesh=mesh, in_specs=None,
                                 out_specs=None)(flag, x)
        """)
        assert _rules(fs) == ["PC201"]
        assert fs[0].qualname == "_yes"
        assert "branch function" in fs[0].message

    def test_straight_line_collective_ok(self):
        fs = _lint("""
            import jax
            from jax.experimental.shard_map import shard_map

            def _body(x):
                return jax.lax.psum(x * 2, "dp")

            def run(mesh, x):
                return shard_map(_body, mesh=mesh, in_specs=None,
                                 out_specs=None)(x)
        """)
        assert "PC201" not in _rules(fs)

    def test_branchy_collective_outside_shard_map_ok(self):
        fs = _lint("""
            import jax

            def helper(x):
                if x.shape[0] > 2:
                    return jax.lax.psum(x, "dp")
                return x
        """)
        assert "PC201" not in _rules(fs)


# ------------------------------------------ CLI: rule listing / filters

class TestCliRuleListing:
    def test_bare_rules_prints_table(self, capsys):
        assert lint_main(["--rules"]) == 0
        out = capsys.readouterr().out
        for rid in ("PT001", "PK101", "PK105", "PC201"):
            assert rid in out
        # one line per rule: id, severity, one-liner
        line = next(ln for ln in out.splitlines()
                    if ln.startswith("PK101"))
        assert "error" in line and "index_map" in line

    def test_list_rules_includes_severity(self, capsys):
        assert lint_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        assert "PK104" in out and "warning" in out

    def test_only_filters(self, tmp_path):
        p = tmp_path / "mod.py"
        p.write_text(textwrap.dedent("""
            import jax

            @jax.jit
            def f(x):
                return float(x)
        """))
        assert lint_main(["--only", "PT006", str(p)]) == 0
        assert lint_main(["--only", "PT001", str(p)]) == 1

    def test_only_unknown_rule_is_usage_error(self, tmp_path):
        p = tmp_path / "mod.py"
        p.write_text("x = 1\n")
        assert lint_main(["--only", "PK999", str(p)]) == 2

    def test_json_rules_carry_severity(self, tmp_path, capsys):
        p = tmp_path / "mod.py"
        p.write_text("x = 1\n")
        assert lint_main(["--json", str(p)]) == 0
        data = json.loads(capsys.readouterr().out)
        assert data["rules"]["PT001"]["severity"] == "error"
        assert data["rules"]["PK105"]["severity"] == "warning"
        assert "description" in data["rules"]["PC201"]


# ----------------------------------------- whole-repo JSON family gate

class TestRepoJsonGate:
    def test_per_family_summary_and_justified_baseline(self, capsys):
        """ISSUE PR8 acceptance: every rule family reports zero fresh
        findings over the real package and the baseline carries no
        unjustified (empty / TODO-stamped) entries."""
        rc = lint_main([os.path.join(REPO, "paddle_tpu"), "--baseline",
                        os.path.join(REPO, "tools",
                                     "paddlelint_baseline.json"),
                        "--json"])
        data = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert data["schema_version"] == 1
        assert set(data["families"]) == {"PT", "PK", "PC", "PS", "PF",
                                         "PE"}
        for fam, info in sorted(data["families"].items()):
            assert info["fresh"] == 0, (fam, data["findings"])
            assert info["rules"], fam
            assert info["unjustified"] == [], fam
        assert data["baseline"]["unjustified"] == []
        assert data["baseline"]["stale"] == []
        # the single accepted PK entry (fusion JIT's definitional oracle)
        assert data["families"]["PK"]["baselined"] == 1
        assert data["families"]["PK"]["per_rule"]["PK105"]["baselined"] == 1
        # the sharding family gates the whole repo at zero: no fresh
        # findings, no baseline debt
        ps = data["families"]["PS"]
        assert ps["rules"] == ["PS301", "PS302", "PS303", "PS304",
                               "PS305", "PS306"]
        assert ps["baselined"] == 0
        assert all(c == {"fresh": 0, "baselined": 0}
                   for c in ps["per_rule"].values())
        # the memory lane gates at zero debt too: all six rules active,
        # nothing fresh, nothing baselined, nothing unjustified
        pf = data["families"]["PF"]
        assert pf["rules"] == ["PF401", "PF402", "PF403", "PF404",
                               "PF405", "PF406"]
        assert pf["baselined"] == 0
        assert all(c == {"fresh": 0, "baselined": 0}
                   for c in pf["per_rule"].values())
        # the effects lane ships with zero debt from day one: all six
        # rules active, nothing fresh, nothing baselined
        pe = data["families"]["PE"]
        assert pe["rules"] == ["PE501", "PE502", "PE503", "PE504",
                               "PE505", "PE506"]
        assert pe["baselined"] == 0
        assert all(c == {"fresh": 0, "baselined": 0}
                   for c in pe["per_rule"].values())
        # and the machine-readable PE505 verdicts certify every PF404
        # candidate plus the registered <=4-launch layer-body
        # composition (ISSUE 20 shipped the old front-half entry as
        # fused_qkv_rope_append)
        verdicts = {v["candidate"]: v for v in data["pe505_verdicts"]}
        comp = next(v for v in data["pe505_verdicts"]
                    if v["composition"] == "decode_layer_le4")
        assert comp["verdict"] == "legal"
        assert verdicts["fused_oproj_norm->fused_ffn"]["verdict"] \
            == "legal"


# -------------------------------------- seeded kernel/collective defects

class TestSeededKernelDefects:
    """ISSUE PR8 acceptance: each PK/PC rule catches exactly its seeded
    defect in a scratch copy of the real kernel modules, and stays quiet
    on the pristine copies. Copies are analyzed statically — never
    imported — so mutations are plain text edits."""

    RAGGED = "paddle_tpu/ops/pallas_ragged.py"
    FUSED = "paddle_tpu/ops/fused.py"

    def _analyze(self, tmp_path, rel, tag, old="", new="", append=""):
        src = open(os.path.join(REPO, rel)).read()
        if old:
            assert old in src, f"seed anchor vanished from {rel}: {old!r}"
            src = src.replace(old, new, 1)
        d = tmp_path / tag
        d.mkdir(exist_ok=True)
        p = d / os.path.basename(rel)   # same rel/modname as the clean
        p.write_text(src + textwrap.dedent(append))
        return analyze_paths([str(p)])

    def _seed(self, tmp_path, rel, **kw):
        clean = self._analyze(tmp_path, rel, "clean")
        seeded = self._analyze(tmp_path, rel, "seeded", **kw)
        new_keys = ({f.baseline_key for f in seeded}
                    - {f.baseline_key for f in clean})
        return [f for f in seeded if f.baseline_key in new_keys]

    def test_pristine_copies_are_quiet(self, tmp_path):
        for rel in (self.RAGGED, self.FUSED):
            fs = self._analyze(tmp_path, rel, "clean")
            assert [f for f in fs if f.rule.startswith(("PK", "PC"))] \
                == [], rel

    def test_pk101_catches_unclamped_page_table_read(self, tmp_path):
        fresh = self._seed(
            tmp_path, self.RAGGED,
            old="phys = jnp.clip(tab[i, jnp.minimum(j, jmax)], 0, "
                "total_pages - 1)",
            new="phys = tab[i, jnp.minimum(j, jmax)]")
        assert fresh and {f.rule for f in fresh} == {"PK101"}
        assert all("tab" in f.detail for f in fresh)

    def test_pk103_catches_widened_alias_dtype(self, tmp_path):
        fresh = self._seed(
            tmp_path, self.FUSED,
            old="jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype)",
            new="jax.ShapeDtypeStruct(k_pages.shape, jnp.float32)")
        assert fresh and {f.rule for f in fresh} == {"PK103"}
        assert any(f.detail.startswith("alias-dtype:7:1:")
                   for f in fresh)

    def test_pk104_catches_bf16_accumulator(self, tmp_path):
        fresh = self._seed(
            tmp_path, self.RAGGED,
            old="scratch_shapes=[pltpu.VMEM((T * rep, D), jnp.float32),",
            new="scratch_shapes=[pltpu.VMEM((T * rep, D), jnp.bfloat16),")
        assert fresh and {f.rule for f in fresh} == {"PK104"}
        assert fresh[0].detail.startswith("acc:")

    def test_pc201_catches_branch_divergent_psum(self, tmp_path):
        fresh = self._seed(tmp_path, self.FUSED, append="""

            from jax.experimental.shard_map import shard_map

            def _seeded_allreduce(x):
                if x.shape[0] > 128:
                    x = jax.lax.psum(x, "dp")
                return x

            def _seeded_launch(mesh, x):
                return shard_map(_seeded_allreduce, mesh=mesh,
                                 in_specs=None, out_specs=None)(x)
            """)
        assert fresh and {f.rule for f in fresh} == {"PC201"}
        assert fresh[0].qualname == "_seeded_allreduce"
        assert fresh[0].detail.startswith("branch-collective:psum:")


# ---------------------------------------------------------------- PS301

class TestPS301UnboundCollectiveAxis:
    def test_psum_over_axis_not_in_mesh(self):
        fs = _lint("""
            import jax
            from jax.sharding import Mesh, PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def f(devs, x):
                mesh = Mesh(devs, ("x", "y"))

                def body(v):
                    return jax.lax.psum(v, "dp")

                return shard_map(body, mesh=mesh, in_specs=(P("x"),),
                                 out_specs=P("x"))(x)
        """)
        assert _rules(fs) == ["PS301"]
        assert fs[0].detail == "unbound-axis:psum:dp"
        assert fs[0].severity == "error"

    def test_axis_present_in_mesh_is_quiet(self):
        fs = _lint("""
            import jax
            from jax.sharding import Mesh, PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def f(devs, x):
                mesh = Mesh(devs, ("x", "y"))

                def body(v):
                    return jax.lax.psum(v, "y")

                return shard_map(body, mesh=mesh, in_specs=(P("x"),),
                                 out_specs=P("x"))(x)
        """)
        assert _rules(fs) == []

    def test_vmap_bound_axis_inside_region_is_quiet(self):
        # body vmaps a helper with its own axis_name: that name is bound
        # even though the mesh doesn't carry it
        fs = _lint("""
            import jax
            from jax.sharding import Mesh, PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def f(devs, x):
                mesh = Mesh(devs, ("x",))

                def inner(u):
                    return jax.lax.psum(u, "v")

                def body(v):
                    return jax.vmap(inner, axis_name="v")(v)

                return shard_map(body, mesh=mesh, in_specs=(P("x"),),
                                 out_specs=P("x"))(x)
        """)
        assert _rules(fs) == []

    def test_symbolic_mesh_axes_are_quiet(self):
        # axis tuple not statically known: must degrade to no finding
        fs = _lint("""
            import jax
            from jax.sharding import Mesh, PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def f(devs, names, x):
                mesh = Mesh(devs, names)

                def body(v):
                    return jax.lax.psum(v, "dp")

                return shard_map(body, mesh=mesh, in_specs=(P("x"),),
                                 out_specs=P("x"))(x)
        """)
        assert _rules(fs) == []


# ---------------------------------------------------------------- PS302

class TestPS302SpecArity:
    def test_more_in_specs_than_body_params(self):
        fs = _lint("""
            import jax
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def f(mesh, x, y):
                def body(v):
                    return v

                return shard_map(body, mesh=mesh, in_specs=(P(), P()),
                                 out_specs=P())(x, y)
        """)
        assert _rules(fs) == ["PS302"]
        assert fs[0].detail == "in-specs-arity:2:1"
        assert fs[0].severity == "error"

    def test_out_specs_tuple_vs_returned_tuple(self):
        fs = _lint("""
            import jax
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def f(mesh, x):
                def body(v):
                    return v, v, v

                return shard_map(body, mesh=mesh, in_specs=(P(),),
                                 out_specs=(P(), P()))(x)
        """)
        assert _rules(fs) == ["PS302"]
        assert fs[0].detail == "out-specs-arity:2:3"

    def test_matching_arity_is_quiet(self):
        fs = _lint("""
            import jax
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def f(mesh, x, y):
                def body(v, w):
                    return v + w

                return shard_map(body, mesh=mesh, in_specs=(P(), P()),
                                 out_specs=P())(x, y)
        """)
        assert _rules(fs) == []

    def test_single_spec_for_any_arity_is_quiet(self):
        # a bare (non-sequence) in_specs broadcasts over all args in the
        # repo's _compat.shard_map — no arity claim to check
        fs = _lint("""
            import jax
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def f(mesh, x, y):
                def body(v, w):
                    return v + w

                return shard_map(body, mesh=mesh, in_specs=P(),
                                 out_specs=P())(x, y)
        """)
        assert _rules(fs) == []


# ---------------------------------------------------------------- PS303

class TestPS303SpecShape:
    def test_duplicate_axis_across_entries(self):
        fs = _lint("""
            from jax.sharding import PartitionSpec as P

            SPEC = P("dp", ("dp", "mp"))
        """)
        assert _rules(fs) == ["PS303"]
        assert fs[0].detail == "dup-axis:dp"
        assert fs[0].severity == "error"

    def test_spec_rank_exceeds_array_rank(self):
        fs = _lint("""
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            def f(mesh):
                arr = jnp.zeros((4, 8))
                return jax.device_put(
                    arr, NamedSharding(mesh, P(None, None, "mp")))
        """)
        assert _rules(fs) == ["PS303"]
        assert fs[0].detail == "rank-excess:3:2"

    def test_trailing_nones_do_not_count_toward_rank(self):
        # P("dp", None) on a rank-1 array: min_rank is 1 after stripping
        fs = _lint("""
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            def f(mesh):
                arr = jnp.zeros((4,))
                return jax.device_put(arr, NamedSharding(mesh, P("dp", None)))
        """)
        assert _rules(fs) == []

    def test_shorter_spec_is_quiet(self):
        fs = _lint("""
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            def f(mesh):
                arr = jnp.zeros((4, 8))
                return jax.device_put(arr, NamedSharding(mesh, P("dp")))
        """)
        assert _rules(fs) == []


# ---------------------------------------------------------------- PS304

class TestPS304Divisibility:
    def test_statically_indivisible_dim(self):
        fs = _lint("""
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P
            from paddle_tpu.distributed.mesh import build_hybrid_mesh

            def f():
                mesh = build_hybrid_mesh(dp_degree=4)
                x = jnp.zeros((6, 128))
                return jax.device_put(x, NamedSharding(mesh, P("dp", None)))
        """)
        assert _rules(fs) == ["PS304"]
        assert fs[0].detail == "indivisible:0:6:4"
        assert fs[0].severity == "warning"

    def test_divisible_dim_is_quiet(self):
        fs = _lint("""
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P
            from paddle_tpu.distributed.mesh import build_hybrid_mesh

            def f():
                mesh = build_hybrid_mesh(dp_degree=4)
                x = jnp.zeros((8, 128))
                return jax.device_put(x, NamedSharding(mesh, P("dp", None)))
        """)
        assert _rules(fs) == []

    def test_symbolic_dim_is_advisory_under_strict_only(self):
        src = """
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            from paddle_tpu.distributed.mesh import build_hybrid_mesh

            def f(x):
                mesh = build_hybrid_mesh(dp_degree=4)
                return jax.device_put(x, NamedSharding(mesh, P("dp", None)))
        """
        assert _rules(_lint(src)) == []
        strict = _lint(src, strict=True)
        assert _rules(strict) == ["PS304"]
        assert strict[0].severity == "info"
        assert strict[0].detail == "indivisible-unverified:0:4"

    def test_unknown_axis_size_is_quiet(self):
        # degree comes in as a parameter: product is symbolic
        fs = _lint("""
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P
            from paddle_tpu.distributed.mesh import build_hybrid_mesh

            def f(n):
                mesh = build_hybrid_mesh(dp_degree=n)
                x = jnp.zeros((6, 128))
                return jax.device_put(x, NamedSharding(mesh, P("dp", None)))
        """)
        assert _rules(fs) == []


# ---------------------------------------------------------------- PS305

class TestPS305AxisShadowing:
    def test_vmap_axis_name_shadows_mesh_axis(self):
        fs = _lint("""
            import jax
            from jax.sharding import Mesh, PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def f(devs, x):
                mesh = Mesh(devs, ("dp", "mp"))

                def inner(u):
                    return u * 2

                def body(v):
                    return jax.vmap(inner, axis_name="dp")(v)

                return shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                                 out_specs=P("dp"))(x)
        """)
        assert _rules(fs) == ["PS305"]
        assert fs[0].detail == "axis-shadow:vmap:dp"
        assert fs[0].severity == "warning"

    def test_distinct_vmap_axis_name_is_quiet(self):
        fs = _lint("""
            import jax
            from jax.sharding import Mesh, PartitionSpec as P
            from jax.experimental.shard_map import shard_map

            def f(devs, x):
                mesh = Mesh(devs, ("dp", "mp"))

                def inner(u):
                    return u * 2

                def body(v):
                    return jax.vmap(inner, axis_name="batch")(v)

                return shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                                 out_specs=P("dp"))(x)
        """)
        assert _rules(fs) == []


# ---------------------------------------------------------------- PS306

class TestPS306UnsanitizedSpec:
    def test_layer_declared_spec_under_ambient_mesh(self):
        fs = _lint("""
            import jax
            from jax.sharding import NamedSharding
            from paddle_tpu.distributed.mesh import get_mesh

            def place(p):
                mesh = get_mesh()
                spec = getattr(p, "_sharding_spec", None)
                return jax.device_put(p, NamedSharding(mesh, spec))
        """)
        assert _rules(fs) == ["PS306"]
        assert fs[0].detail == "unsanitized-layer-spec"
        assert fs[0].severity == "warning"

    def test_sanitized_layer_spec_is_quiet(self):
        fs = _lint("""
            import jax
            from jax.sharding import NamedSharding
            from paddle_tpu.distributed.mesh import get_mesh, sanitize_spec

            def place(p):
                mesh = get_mesh()
                spec = sanitize_spec(mesh, getattr(p, "_sharding_spec", None))
                return jax.device_put(p, NamedSharding(mesh, spec))
        """)
        assert _rules(fs) == []

    def test_literal_axes_under_ambient_mesh(self):
        fs = _lint("""
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            from paddle_tpu.distributed.mesh import get_mesh

            def place(x):
                mesh = get_mesh()
                return jax.device_put(x, NamedSharding(mesh, P("mp")))
        """)
        assert _rules(fs) == ["PS306"]
        assert fs[0].detail == "unsanitized-spec:mp"

    def test_parameter_mesh_with_literal_spec_is_quiet(self):
        # a mesh handed in by the caller is a contract, not a
        # configuration point — pretrain.py's pattern
        fs = _lint("""
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            def place(mesh, x):
                return jax.device_put(x, NamedSharding(mesh, P("mp")))
        """)
        assert _rules(fs) == []

    def test_known_mesh_covering_spec_axes_is_quiet(self):
        # env is complete and contains every axis the spec names
        fs = _lint("""
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            from paddle_tpu.distributed.mesh import build_hybrid_mesh

            def place(x):
                mesh = build_hybrid_mesh(mp_degree=4)
                return jax.device_put(x, NamedSharding(mesh, P("mp")))
        """)
        assert _rules(fs) == []


# ----------------------------------------- seeded sharding/mesh defects

class TestSeededShardingDefects:
    """ISSUE PR9 acceptance: each PS rule catches exactly its seeded
    defect in a scratch copy of the real distributed modules, and stays
    quiet on the pristine copies. Copies are analyzed statically — never
    imported — so mutations are plain text edits."""

    MESH = "paddle_tpu/distributed/mesh.py"
    PP_EXEC = "paddle_tpu/distributed/pp_exec.py"
    SHARDING = "paddle_tpu/distributed/sharding.py"

    def _analyze(self, tmp_path, rel, tag, old="", new="", append=""):
        src = open(os.path.join(REPO, rel)).read()
        if old:
            assert old in src, f"seed anchor vanished from {rel}: {old!r}"
            src = src.replace(old, new, 1)
        d = tmp_path / tag
        d.mkdir(exist_ok=True)
        p = d / os.path.basename(rel)   # same rel/modname as the clean
        p.write_text(src + textwrap.dedent(append))
        return analyze_paths([str(p)])

    def _seed(self, tmp_path, rel, **kw):
        clean = self._analyze(tmp_path, rel, "clean")
        seeded = self._analyze(tmp_path, rel, "seeded", **kw)
        new_keys = ({f.baseline_key for f in seeded}
                    - {f.baseline_key for f in clean})
        return [f for f in seeded if f.baseline_key in new_keys]

    def test_pristine_copies_are_quiet(self, tmp_path):
        for rel in (self.MESH, self.PP_EXEC, self.SHARDING):
            fs = self._analyze(tmp_path, rel, "clean")
            assert [f for f in fs if f.rule.startswith("PS")] == [], rel

    def test_ps301_catches_psum_over_missing_axis(self, tmp_path):
        fresh = self._seed(tmp_path, self.MESH, append="""

            from jax.experimental.shard_map import shard_map

            def _seed_allreduce(x):
                mesh = build_hybrid_mesh(dp_degree=4, mp_degree=2)

                def body(v):
                    return jax.lax.psum(v, "tp")

                return shard_map(body, mesh=mesh,
                                 in_specs=(PartitionSpec("dp"),),
                                 out_specs=PartitionSpec("dp"))(x)
            """)
        assert fresh and {f.rule for f in fresh} == {"PS301"}
        assert fresh[0].detail == "unbound-axis:psum:tp"

    def test_ps302_catches_spec_arity_mismatch(self, tmp_path):
        fresh = self._seed(tmp_path, self.MESH, append="""

            from jax.experimental.shard_map import shard_map

            def _seed_badarity(x, y):
                mesh = build_hybrid_mesh(dp_degree=4)

                def body(v):
                    return v

                return shard_map(body, mesh=mesh,
                                 in_specs=(PartitionSpec("dp"),
                                           PartitionSpec()),
                                 out_specs=PartitionSpec("dp"))(x, y)
            """)
        assert fresh and {f.rule for f in fresh} == {"PS302"}
        assert fresh[0].detail == "in-specs-arity:2:1"

    def test_ps303_catches_dup_axis_and_rank_excess(self, tmp_path):
        fresh = self._seed(tmp_path, self.MESH, append="""

            import jax.numpy as jnp

            def _seed_badspec(mesh):
                arr = jnp.zeros((4, 8))
                spec = PartitionSpec("dp", ("dp", "mp"))
                return jax.device_put(
                    arr, NamedSharding(mesh, PartitionSpec(None, None, "mp")))
            """)
        assert fresh and {f.rule for f in fresh} == {"PS303"}
        assert {f.detail for f in fresh} == {"dup-axis:dp", "rank-excess:3:2"}

    def test_ps304_catches_indivisible_dim(self, tmp_path):
        fresh = self._seed(tmp_path, self.MESH, append="""

            import jax.numpy as jnp

            def _seed_indivisible():
                mesh = build_hybrid_mesh(dp_degree=4)
                x = jnp.zeros((6, 128))
                return jax.device_put(
                    x, NamedSharding(mesh, PartitionSpec("dp", None)))
            """)
        assert fresh and {f.rule for f in fresh} == {"PS304"}
        assert fresh[0].detail == "indivisible:0:6:4"

    def test_ps305_catches_vmap_axis_shadow(self, tmp_path):
        fresh = self._seed(tmp_path, self.MESH, append="""

            from jax.experimental.shard_map import shard_map

            def _seed_shadow(x):
                mesh = build_hybrid_mesh(dp_degree=4)

                def inner(u):
                    return u * 2

                def body(v):
                    return jax.vmap(inner, axis_name="dp")(v)

                return shard_map(body, mesh=mesh,
                                 in_specs=(PartitionSpec("dp"),),
                                 out_specs=PartitionSpec("dp"))(x)
            """)
        assert fresh and {f.rule for f in fresh} == {"PS305"}
        assert fresh[0].detail == "axis-shadow:vmap:dp"

    def test_ps306_catches_dropped_sanitize_in_sharding(self, tmp_path):
        fresh = self._seed(
            tmp_path, self.SHARDING,
            old='        base = sanitize_spec(mesh, getattr(p, '
                '"_sharding_spec", None))\n'
                '        spec = compose_sharding_spec(base, arr.shape, '
                'axis, size)',
            new='        spec = getattr(p, "_sharding_spec", None)')
        assert fresh and {f.rule for f in fresh} == {"PS306"}
        assert fresh[0].detail == "unsanitized-layer-spec"


# ------------------------------------------------- --changed-only mode

class TestChangedOnly:
    def _repo(self, tmp_path):
        """A tiny git repo: committed clean module + uncommitted leaky
        one. --changed-only must analyze only the latter."""
        import subprocess
        def git(*a):
            subprocess.run(["git", *a], cwd=tmp_path, check=True,
                           capture_output=True,
                           env={**os.environ,
                                "GIT_AUTHOR_NAME": "t",
                                "GIT_AUTHOR_EMAIL": "t@t",
                                "GIT_COMMITTER_NAME": "t",
                                "GIT_COMMITTER_EMAIL": "t@t"})
        git("init", "-q")
        (tmp_path / "clean.py").write_text(textwrap.dedent("""
            import jax

            @jax.jit
            def g(x):
                return float(x)
        """))
        git("add", "clean.py")
        git("commit", "-qm", "seed")
        (tmp_path / "leaky.py").write_text(textwrap.dedent("""
            import jax

            @jax.jit
            def f(x):
                return float(x)
        """))
        git("add", "leaky.py")  # staged => in `git diff HEAD`
        return tmp_path

    def test_only_changed_files_analyzed(self, tmp_path, capsys,
                                         monkeypatch):
        repo = self._repo(tmp_path)
        monkeypatch.chdir(repo)
        rc = lint_main(["--changed-only", "HEAD", "--json",
                        str(repo / "clean.py"), str(repo / "leaky.py")])
        data = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert data["changed_only"]["ref"] == "HEAD"
        assert data["changed_only"]["files"] == ["leaky.py"]
        # the committed-clean module's finding is NOT reported
        assert {f["path"] for f in data["findings"]} == {"leaky.py"}
        assert data["stale_baseline_keys"] == []

    def test_no_changes_exits_zero(self, tmp_path, capsys, monkeypatch):
        repo = self._repo(tmp_path)
        import subprocess
        subprocess.run(["git", "add", "-A"], cwd=repo, check=True)
        subprocess.run(["git", "commit", "-qm", "all"], cwd=repo,
                       check=True, capture_output=True,
                       env={**os.environ,
                            "GIT_AUTHOR_NAME": "t",
                            "GIT_AUTHOR_EMAIL": "t@t",
                            "GIT_COMMITTER_NAME": "t",
                            "GIT_COMMITTER_EMAIL": "t@t"})
        monkeypatch.chdir(repo)
        rc = lint_main(["--changed-only", "--json",
                        str(repo / "clean.py"), str(repo / "leaky.py")])
        data = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert data["changed_only"]["files"] == []
        assert data["findings"] == []

    def test_git_unavailable_falls_back_to_full_run(self, tmp_path,
                                                    capsys, monkeypatch):
        # no .git anywhere up from tmp_path/sub: `git diff` fails and the
        # CLI analyzes everything, warning on stderr
        sub = tmp_path / "sub"
        sub.mkdir()
        (sub / "leaky.py").write_text(textwrap.dedent("""
            import jax

            @jax.jit
            def f(x):
                return float(x)
        """))
        monkeypatch.chdir(sub)
        monkeypatch.setenv("GIT_DIR", str(sub / "nonexistent"))
        # path first: a greedy `--changed-only PATH` would read the
        # path as its optional REF value
        rc = lint_main([str(sub / "leaky.py"), "--changed-only"])
        cap = capsys.readouterr()
        assert rc == 1
        assert "git unavailable" in cap.err
        assert "PT001" in cap.out


# ------------------------------- changed-only factory-module expansion

class TestChangedOnlyFactoryExpansion:
    """ISSUE PR13 small fix: a kernel built in one module (the factory)
    and launched from another anchors its findings at the pallas_call
    site — so when only the factory file changes, `--changed-only` must
    pull the call-site file back into the analyzed set or the defect the
    edit introduced is silently skipped."""

    FACTORY = """
        def make_kernel(eps):
            def _kern(x_ref, y_ref, o_ref):
                o_ref[:] = x_ref[:] + eps
            return _kern
    """
    CALLSITE = """
        import jax
        from jax.experimental import pallas as pl

        from pkg.factory import make_kernel

        def run(x):
            kern = make_kernel(0.5)
            return pl.pallas_call(
                kern,
                grid=(4,),
                in_specs=[pl.BlockSpec((1, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((1, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(x)
    """

    def _pkg(self, tmp_path):
        from paddle_tpu.analysis.runner import discover
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "factory.py").write_text(textwrap.dedent(self.FACTORY))
        (pkg / "callsite.py").write_text(textwrap.dedent(self.CALLSITE))
        return pkg, discover(str(pkg))

    def test_factory_change_pulls_in_call_site(self, tmp_path):
        from paddle_tpu.analysis.runner import (
            analyze_files, expand_changed_with_factories)
        pkg, files = self._pkg(tmp_path)
        changed = {os.path.abspath(str(pkg / "factory.py"))}
        sel = expand_changed_with_factories(files, changed)
        assert sorted(t[2] for t in sel) == ["pkg/callsite.py",
                                             "pkg/factory.py"]
        fs = analyze_files(sel, Config(rules={"PK102"}))
        assert [(f.rule, f.path, f.detail) for f in fs] \
            == [("PK102", "pkg/callsite.py", "refs:3!=2")]

    def test_naive_selection_misses_the_defect(self, tmp_path):
        # the regression this guards: filtering by changed paths alone
        # analyzes only the factory file, where no pallas_call site
        # exists, and the ref-count mismatch goes unreported
        from paddle_tpu.analysis.runner import analyze_files
        pkg, files = self._pkg(tmp_path)
        changed = {os.path.abspath(str(pkg / "factory.py"))}
        naive = [t for t in files
                 if os.path.abspath(t[1]) in changed]
        assert analyze_files(naive, Config(rules={"PK102"})) == []

    def test_call_site_change_is_not_duplicated(self, tmp_path):
        from paddle_tpu.analysis.runner import (
            expand_changed_with_factories)
        pkg, files = self._pkg(tmp_path)
        changed = {os.path.abspath(str(pkg / "factory.py")),
                   os.path.abspath(str(pkg / "callsite.py"))}
        sel = expand_changed_with_factories(files, changed)
        assert sorted(t[2] for t in sel) == ["pkg/callsite.py",
                                             "pkg/factory.py"]

    def test_no_changes_selects_nothing(self, tmp_path):
        from paddle_tpu.analysis.runner import (
            expand_changed_with_factories)
        _, files = self._pkg(tmp_path)
        assert expand_changed_with_factories(files, set()) == []


# ------------------------------------ JSON schema version + ordering

class TestJsonSchemaAndOrdering:
    def test_schema_version_present(self, tmp_path, capsys):
        p = tmp_path / "mod.py"
        p.write_text("x = 1\n")
        assert lint_main(["--json", str(p)]) == 0
        data = json.loads(capsys.readouterr().out)
        assert data["schema_version"] == 1

    def test_findings_sorted_rule_path_qualname(self, tmp_path, capsys):
        # two files, two rules each — emitted order must be
        # (rule, path, qualname), not discovery or pass order
        for name in ("b_mod.py", "a_mod.py"):
            (tmp_path / name).write_text(textwrap.dedent("""
                import jax

                @jax.jit
                def f(x):
                    if x > 0:          # PT001 branch on traced value
                        x = float(x)   # PT001 host conversion
                    return x

                def loop():
                    for _ in range(3):
                        g = jax.jit(lambda y: y)   # PT002
                    return g
            """))
        assert lint_main(["--json", str(tmp_path / "b_mod.py"),
                          str(tmp_path / "a_mod.py")]) == 1
        data = json.loads(capsys.readouterr().out)
        keys = [(f["rule"], f["path"], f["qualname"])
                for f in data["findings"]]
        assert keys == sorted(keys)
        assert len({f["rule"] for f in data["findings"]}) > 1
        assert len({f["path"] for f in data["findings"]}) > 1

    def test_rules_carry_module(self, tmp_path, capsys):
        p = tmp_path / "mod.py"
        p.write_text("x = 1\n")
        assert lint_main(["--json", str(p)]) == 0
        data = json.loads(capsys.readouterr().out)
        assert data["rules"]["PC201"]["module"].endswith(
            "rules_collective")
        assert data["rules"]["PF401"]["module"].endswith("rules_memory")


# ---------------------------------------------- rule-family registry

class TestRuleFamilyRegistry:
    def test_every_rule_has_a_module_and_family(self):
        from paddle_tpu.analysis.model import (FAMILIES, RULE_MODULES,
                                               RULES, rule_family)
        for rid in RULES:
            assert RULE_MODULES.get(rid), rid
            assert rule_family(rid) in FAMILIES, rid

    def test_pc201_mapping_documented_in_registry(self):
        # PC201 lives in rules_collective.py by design; the registry —
        # not the filename convention — records that
        from paddle_tpu.analysis.model import RULE_MODULES
        assert RULE_MODULES["PC201"].endswith(".rules_collective")

    def test_list_rules_grouped_by_family(self, capsys):
        assert lint_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        headers = [ln for ln in out.splitlines() if ln.startswith("-- ")]
        assert [h.split()[1].rstrip(":") for h in headers] \
            == ["PC", "PE", "PF", "PK", "PS", "PT"]
        # rules listed under their family header
        lines = out.splitlines()
        pf_at = lines.index(next(h for h in headers if "PF" in h))
        pk_at = lines.index(next(h for h in headers if "PK" in h))
        pf401_at = next(i for i, ln in enumerate(lines)
                        if ln.startswith("PF401"))
        assert pf_at < pf401_at < pk_at
        # cross-filed rules carry their module marker
        pt003 = next(ln for ln in lines if ln.startswith("PT003"))
        assert "rules_hostsync" in pt003


# ------------------------------------------ seeded memory-lane defects

class TestSeededMemoryDefects:
    """ISSUE PR13 acceptance: each PF rule catches exactly its seeded
    defect in a scratch copy of the real kernel modules, and the
    pristine copies stay PF-quiet. Copies are analyzed statically —
    never imported — so mutations are plain text edits."""

    RAGGED = "paddle_tpu/ops/pallas_ragged.py"
    FUSED = "paddle_tpu/ops/fused.py"
    QUANT = "paddle_tpu/ops/quant.py"
    MEGADECODE = "paddle_tpu/ops/pallas_megadecode.py"
    MEGAFRONT = "paddle_tpu/ops/pallas_megafront.py"

    def _analyze(self, tmp_path, rel, tag, old="", new="", append="",
                 strict=False):
        src = open(os.path.join(REPO, rel)).read()
        if old:
            assert old in src, f"seed anchor vanished from {rel}: {old!r}"
            src = src.replace(old, new, 1)
        d = tmp_path / tag
        d.mkdir(exist_ok=True)
        p = d / os.path.basename(rel)
        p.write_text(src + textwrap.dedent(append))
        return analyze_paths([str(p)], Config(strict=strict))

    def _seed(self, tmp_path, rel, strict=False, **kw):
        clean = self._analyze(tmp_path, rel, "clean", strict=strict)
        seeded = self._analyze(tmp_path, rel, "seeded", strict=strict,
                               **kw)
        new_keys = ({f.baseline_key for f in seeded}
                    - {f.baseline_key for f in clean})
        return [f for f in seeded if f.baseline_key in new_keys]

    def test_pristine_copies_are_pf_quiet(self, tmp_path):
        for rel in (self.RAGGED, self.FUSED, self.QUANT,
                    self.MEGADECODE):
            fs = self._analyze(tmp_path, rel, "clean")
            assert [f for f in fs if f.rule.startswith("PF")] == [], rel

    def test_pf401_catches_vmem_overflow(self, tmp_path):
        # 4096x the f32 accumulator scratch: ~64 MiB against the 16 MiB
        # per-core budget
        fresh = self._seed(
            tmp_path, self.RAGGED,
            old="pltpu.VMEM((T * rep, D), jnp.float32),",
            new="pltpu.VMEM((T * rep * 4096, D), jnp.float32),")
        assert fresh and {f.rule for f in fresh} == {"PF401"}
        assert fresh[0].detail == "vmem:ragged_paged_attention"
        assert "MiB" in fresh[0].message

    def test_pf402_catches_read_after_donate(self, tmp_path):
        # `pages` is donated to output 0 of fused_append_rows; reading
        # it after the launch observes the in-place overwrite
        fresh = self._seed(
            tmp_path, self.FUSED,
            old="      rows, pages)",
            new="      rows, pages)\n    _ = pages.mean()")
        assert fresh and {f.rule for f in fresh} == {"PF402"}
        assert fresh[0].detail == "alias:pages->out0"
        assert fresh[0].qualname == "fused_append_rows"

    def test_pf403_catches_reduced_precision_accumulator_store(
            self, tmp_path):
        # scratch stays DECLARED f32 (PK104 quiet) but the store
        # truncates — the break PK104's declaration-side check misses
        fresh = self._seed(
            tmp_path, self.RAGGED,
            old="m_ref[:] = m_new",
            new="m_ref[:] = m_new.astype(jnp.bfloat16)")
        assert fresh and {f.rule for f in fresh} == {"PF403"}
        assert fresh[0].detail == "accum:m_ref"

    def test_pf403_catches_unaligned_int4_lane(self, tmp_path):
        # a Name-bound lane block (not a literal, so PK102's constant
        # lane check stays quiet) that breaks the nibble-packed 128
        # alignment
        fresh = self._seed(
            tmp_path, self.QUANT,
            old="bn = next((c for c in (2048, 1024, 512, 256, 128) "
                "if Np % c == 0), Np)",
            new="bn = 64")
        assert fresh and {f.rule for f in fresh} == {"PF403"}
        assert fresh[0].detail == "int4lane:bn"
        assert fresh[0].qualname == "int4_dequantize"

    def test_pf404_emits_decode_chain_fusion_worklist(self, tmp_path):
        # advisory, info severity: pristine copies of the three chain
        # modules are the fixture.  ISSUE 14 RESOLVED the old
        # rms->swiglu advisory and ISSUE 20 the rms->rope seam (those
        # pairs now live inside the mega-kernels); what remains is the
        # norm->front retile (the registered <=4-launch follow-on) and
        # the deliberate oproj->ffn seam the mega-kernels keep (VMEM
        # weight budget — see DECODE_CHAIN's comment)
        d = tmp_path / "chain"
        d.mkdir()
        paths = []
        for rel in (self.FUSED, self.MEGADECODE, self.MEGAFRONT):
            p = d / os.path.basename(rel)
            p.write_text(open(os.path.join(REPO, rel)).read())
            paths.append(str(p))
        fs = analyze_paths(paths, Config(strict=True))
        details = {f.detail for f in fs if f.rule == "PF404"}
        assert details == {
            "fuse:fused_rms_norm->fused_qkv_rope_append",
            "fuse:fused_oproj_norm->fused_ffn"}
        # ...and stays out of default (non-strict) runs
        fs = analyze_paths(paths, Config(strict=False))
        assert [f for f in fs if f.rule == "PF404"] == []

    def test_pf405_catches_indivisible_grid(self, tmp_path):
        # 8 tokens // 192 == 0 under the canonical shapes: the launch
        # silently skips every row
        fresh = self._seed(
            tmp_path, self.FUSED,
            old="grid=(T // bt,),",
            new="grid=(T // 192,),")
        assert fresh and {f.rule for f in fresh} == {"PF405"}
        assert fresh[0].detail == "grid:T // 192"
        assert fresh[0].qualname == "_rms_forward"

    def test_pf406_catches_cost_model_drift(self, tmp_path):
        # grow the dequant output block ~25%: BlockSpec-derived bytes
        # drift past COST_DRIFT_RTOL while VMEM stays in budget, so
        # exactly the drift rule fires
        fresh = self._seed(
            tmp_path, self.QUANT,
            old="out_specs=pl.BlockSpec((K2 * 2, bn), "
                "lambda j: (0, j)),",
            new="out_specs=pl.BlockSpec((K2 * 2 + 256, bn), "
                "lambda j: (0, j)),")
        # PE506 (ISSUE 19) attributes the same drift to the write side
        assert fresh and {f.rule for f in fresh} == {"PF406", "PE506"}
        assert any(f.detail == "drift:int4_dequantize" for f in fresh)


# ------------------------------------------------------ DCN tier (PS3xx)

class TestDCNTierAxes:
    """ISSUE 15: build_hybrid_mesh grew an explicit multi-slice DCN tier
    (dcn_dp/dcn_pp, outermost). The static mesh model must know the new
    axes — both the keyword degrees and the extended positional order —
    so the PS rules check DCN-tier layouts like any other axis."""

    def test_dcn_dp_statically_indivisible_dim(self):
        fs = _lint("""
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P
            from paddle_tpu.distributed.mesh import build_hybrid_mesh

            def f():
                mesh = build_hybrid_mesh(dcn_dp_degree=4)
                x = jnp.zeros((6, 128))
                return jax.device_put(
                    x, NamedSharding(mesh, P("dcn_dp", None)))
        """)
        assert _rules(fs) == ["PS304"]
        assert fs[0].detail == "indivisible:0:6:4"

    def test_dcn_dp_divisible_dim_is_quiet(self):
        fs = _lint("""
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P
            from paddle_tpu.distributed.mesh import build_hybrid_mesh

            def f():
                mesh = build_hybrid_mesh(dcn_dp_degree=4)
                x = jnp.zeros((8, 128))
                return jax.device_put(
                    x, NamedSharding(mesh, P("dcn_dp", None)))
        """)
        assert _rules(fs) == []

    def test_dcn_pp_positional_degree(self):
        # positional signature tail: ..., ep, dcn_dp, dcn_pp
        fs = _lint("""
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P
            from paddle_tpu.distributed.mesh import build_hybrid_mesh

            def f():
                mesh = build_hybrid_mesh(1, 1, 1, 1, 1, 1, 1, 4)
                x = jnp.zeros((6, 128))
                return jax.device_put(
                    x, NamedSharding(mesh, P("dcn_pp", None)))
        """)
        assert _rules(fs) == ["PS304"]
        assert fs[0].detail == "indivisible:0:6:4"

    def test_psum_over_dcn_axis_is_bound(self):
        # the hybrid mesh carries the dcn axes even at degree 1: a
        # collective over them is bound, not a PS301 unbound-axis error
        fs = _lint("""
            import jax
            from jax.sharding import PartitionSpec as P
            from jax.experimental.shard_map import shard_map
            from paddle_tpu.distributed.mesh import build_hybrid_mesh

            def f(x):
                mesh = build_hybrid_mesh()

                def body(v):
                    return jax.lax.psum(v, "dcn_dp")

                return shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                                 out_specs=P("dp"))(x)
        """)
        assert _rules(fs) == []


# ---------------------------------------- seeded effects-lane defects

class TestSeededEffectsDefects:
    """ISSUE 19 acceptance: each PE rule catches exactly its seeded
    hazard in a scratch copy of the real kernel modules (alias swap,
    dropped accumulator guard, widened scatter, overlapping output
    index_map, fused-pair read/write inversion, write-side cost edit),
    and the pristine copies report zero fresh PE findings.  Copies are
    analyzed statically — never imported."""

    RAGGED = "paddle_tpu/ops/pallas_ragged.py"
    FUSED = "paddle_tpu/ops/fused.py"
    MEGADECODE = "paddle_tpu/ops/pallas_megadecode.py"
    MEGAFRONT = "paddle_tpu/ops/pallas_megafront.py"
    PAGED = "paddle_tpu/ops/pallas_paged.py"
    FLASHMASK = "paddle_tpu/ops/pallas_flashmask.py"

    def _analyze(self, tmp_path, rel, tag, old="", new="",
                 strict=False, extra=()):
        src = open(os.path.join(REPO, rel)).read()
        if old:
            assert old in src, f"seed anchor vanished from {rel}: {old!r}"
            src = src.replace(old, new, 1)
        d = tmp_path / tag
        d.mkdir(exist_ok=True)
        p = d / os.path.basename(rel)
        p.write_text(src)
        paths = [str(p)]
        for x in extra:        # pristine companions (cross-module
            q = d / os.path.basename(x)   # compositions need all sites)
            q.write_text(open(os.path.join(REPO, x)).read())
            paths.append(str(q))
        return analyze_paths(paths, Config(strict=strict))

    def _seed(self, tmp_path, rel, strict=False, extra=(), **kw):
        clean = self._analyze(tmp_path, rel, "clean", strict=strict,
                              extra=extra)
        seeded = self._analyze(tmp_path, rel, "seeded", strict=strict,
                               extra=extra, **kw)
        new_keys = ({f.baseline_key for f in seeded}
                    - {f.baseline_key for f in clean})
        return [f for f in seeded if f.baseline_key in new_keys]

    def test_pristine_copies_are_pe_quiet(self, tmp_path):
        for rel in (self.RAGGED, self.FUSED, self.MEGADECODE,
                    self.MEGAFRONT, self.PAGED, self.FLASHMASK):
            fs = self._analyze(tmp_path, rel, "clean")
            assert [f for f in fs if f.rule.startswith("PE")] == [], rel

    def test_pe501_catches_overlapping_output_index_map(self, tmp_path):
        # pin _rms_forward's output block to (0, 0): every grid step now
        # writes the same block, with no dimension_semantics declaring
        # the axis sequential
        # megafront rides along pristine so the layer-body composition
        # (whose members span both modules) resolves on both sides
        fresh = self._seed(
            tmp_path, self.FUSED, extra=(self.MEGAFRONT,),
            old="out_specs=pl.BlockSpec((bt, H), lambda i: (i, 0)),\n"
                "        out_shape=jax.ShapeDtypeStruct((T, H), "
                "x2.dtype),",
            new="out_specs=pl.BlockSpec((bt, H), lambda i: (0, 0)),\n"
                "        out_shape=jax.ShapeDtypeStruct((T, H), "
                "x2.dtype),")
        assert fresh and "PE501" in {f.rule for f in fresh}
        pe = next(f for f in fresh if f.rule == "PE501")
        assert pe.qualname == "_rms_forward"
        assert pe.detail == "ww:o_ref:ax0"
        # the poisoned member also flips the fusion verdict to hazard
        assert any(f.rule == "PE505" and
                   f.detail.startswith("fusehazard:") for f in fresh)

    def test_pe502_catches_swapped_alias_indices(self, tmp_path):
        # cross the donated page pools: vin_ref now aliases kp_ref,
        # which the kernel seeds BEFORE vin_ref's read
        fresh = self._seed(
            tmp_path, self.FUSED,
            old="input_output_aliases={7: 1, 8: 2}",
            new="input_output_aliases={7: 2, 8: 1}")
        assert fresh and "PE502" in {f.rule for f in fresh}
        pe = next(f for f in fresh if f.rule == "PE502")
        assert pe.detail == "radw:vin_ref->kp_ref"
        assert pe.qualname == "fused_rope_append"

    def test_pe503_catches_dropped_accumulator_guard(self, tmp_path):
        # delete the @pl.when(j == 0) decorator: _init becomes dead
        # code (never called), so the online-softmax state is read by
        # the last-step emit with no first-step seed
        fresh = self._seed(
            tmp_path, self.RAGGED,
            old="    @pl.when(j == 0)\n    def _init():",
            new="    def _init():")
        assert fresh and {f.rule for f in fresh} == {"PE503"}
        assert {f.detail for f in fresh} \
            == {"acc:acc_ref", "acc:m_ref", "acc:l_ref"}

    def test_pe504_catches_widened_scatter(self, tmp_path):
        # widen the paged-append row scatter to two rows: adjacent
        # table offsets may differ by one, so step t and t+1 overlap
        fresh = self._seed(
            tmp_path, self.FUSED,
            old="kp_ref[:, 0, pl.dslice(off, 1), :]",
            new="kp_ref[:, 0, pl.dslice(off, 2), :]")
        assert fresh and "PE504" in {f.rule for f in fresh}
        pe = next(f for f in fresh if f.rule == "PE504")
        assert pe.detail == "scatter:kp_ref:w2"
        assert pe.severity == "error"

    def test_pe504_contract_note_under_strict(self, tmp_path):
        # the clean width-1 table scatter surfaces as an info note
        # (proven under the append contract) only with --strict
        fs = self._analyze(tmp_path, self.FUSED, "clean", strict=True)
        details = {f.detail for f in fs if f.rule == "PE504"}
        assert details == {"scatter-contract:kp_ref",
                           "scatter-contract:vp_ref",
                           "scatter-contract:po_ref"}
        assert all(f.severity == "info" for f in fs
                   if f.rule == "PE504")

    def test_pe505_catches_read_write_inversion(self, tmp_path):
        # shift fused_ffn's consumed-block index by one: the fused
        # launch would read a block its producer has not written yet
        fresh = self._seed(
            tmp_path, self.MEGADECODE,
            old="in_specs=[pl.BlockSpec((bt, H), lambda i: (i, 0)),",
            new="in_specs=[pl.BlockSpec((bt, H), "
                "lambda i: (i + 1, 0)),")
        assert fresh and {f.rule for f in fresh} == {"PE505"}
        details = {f.detail for f in fresh}
        # the pair candidate flips AND the layer-body composition that
        # contains it inherits the hazard
        assert "fusehazard:fused_oproj_norm->fused_ffn" in details
        assert ("fusehazard:fused_rms_norm->fused_qkv_rope_append->"
                "fused_oproj_norm->fused_ffn") in details
        pe = next(f for f in fresh if f.detail
                  == "fusehazard:fused_oproj_norm->fused_ffn")
        assert pe.severity == "error"
        # the hazard names the refs on both sides of the seam
        assert "xo_ref" in pe.message and "h_ref" in pe.message
        assert "read/write inversion" in pe.message

    def test_pe505_flips_illegal_on_retiled_megafront_out_spec(
            self, tmp_path):
        # ISSUE 20 acceptance: pin the fused front's q out-spec to
        # block (0, 0, 0).  The kernel's own launch stays PE501-quiet
        # (the token axis is declared arbitrary for the page scatter),
        # but the q stream no longer tiles the way downstream members
        # consume it, so the shipped layer-body composition's verdict
        # must flip from legal to hazard
        fresh = self._seed(
            tmp_path, self.MEGAFRONT,
            extra=(self.FUSED, self.MEGADECODE),
            old="out_specs=[pl.BlockSpec((1, heads, D), "
                "lambda t, pg, off: (t, 0, 0)),",
            new="out_specs=[pl.BlockSpec((1, heads, D), "
                "lambda t, pg, off: (0, 0, 0)),")
        hazards = [f for f in fresh if f.rule == "PE505"
                   and f.detail.startswith("fusehazard:")]
        assert hazards
        comp = next(f for f in hazards if f.detail ==
                    "fusehazard:fused_rms_norm->fused_qkv_rope_append"
                    "->fused_oproj_norm->fused_ffn")
        assert comp.severity == "error"
        assert "read/write inversion" in comp.message
        assert "qo_ref" in comp.message

    def test_pe506_catches_write_side_drift(self, tmp_path):
        # halve the rope output block's lane extent: written bytes
        # drop 50% below costmodel.bytes_written (PF406 fires on the
        # total too — PE506 is the write-side attribution)
        fresh = self._seed(
            tmp_path, self.FUSED,
            old="out_specs=pl.BlockSpec((1, bs, H, D), "
                "lambda b, i: (b, i, 0, 0)),",
            new="out_specs=pl.BlockSpec((1, bs, H, D // 2), "
                "lambda b, i: (b, i, 0, 0)),")
        assert fresh and "PE506" in {f.rule for f in fresh}
        pe = next(f for f in fresh if f.rule == "PE506")
        assert pe.detail == "wdrift:fused_rope"
        assert pe.qualname == "_rope_forward"

    def test_pe503_accepts_dma_filled_scratch(self, tmp_path):
        # paged v2's kbuf/vbuf double buffers are filled through
        # buf.at[...] DMA handles the scanner cannot order — they must
        # degrade to unknown, not fire PE503
        fs = self._analyze(tmp_path, self.PAGED, "clean")
        assert [f for f in fs if f.rule == "PE503"] == []

    def test_pe501_flashmask_declares_revisited_axis(self, tmp_path):
        # regression for the fix this PR ships: the flashmask launches
        # now declare the innermost (revisited) axis "arbitrary"; strip
        # the declaration and PE501 fires on the helper-built out specs
        fresh = self._seed(
            tmp_path, self.FLASHMASK,
            old="        compiler_params=_CPARAMS,\n"
                "        interpret=_interpret(),\n"
                "    )(kinds, s1, e1, s2, e2, q, k, v)",
            new="        interpret=_interpret(),\n"
                "    )(kinds, s1, e1, s2, e2, q, k, v)")
        assert fresh and "PE501" in {f.rule for f in fresh}
        pe = [f for f in fresh if f.rule == "PE501"]
        assert {f.detail for f in pe} == {"ww:o_ref:ax3",
                                          "ww:lse_ref:ax3"}


# --------------------------------- serving modules: no-clock regression

class TestServingModulesLintClean:
    """ISSUE 19 satellite: the PR 17-18 serving modules claim a no-clock
    discipline (feedback control without host-time branches on the hot
    path) — lock in zero fresh PT/PC findings so a future edit cannot
    silently reintroduce host syncs or branch-divergent collectives."""

    MODULES = ("paddle_tpu/serving/controller.py",
               "paddle_tpu/serving/router.py")

    def test_controller_and_router_have_no_pt_pc_findings(self):
        for rel in self.MODULES:
            fs = analyze_paths([os.path.join(REPO, rel)])
            bad = [f for f in fs if f.rule.startswith(("PT", "PC"))]
            assert bad == [], (rel, [(f.rule, f.detail) for f in bad])

    def test_modules_are_clean_even_under_strict(self):
        for rel in self.MODULES:
            fs = analyze_paths([os.path.join(REPO, rel)],
                               Config(strict=True))
            assert fs == [], (rel, [(f.rule, f.detail) for f in fs])


# --------------------------------------------------- SARIF CI output

class TestSarifOutput:
    def test_sarif_file_carries_findings_and_rules(self, tmp_path,
                                                   capsys):
        p = tmp_path / "mod.py"
        p.write_text(textwrap.dedent("""
            import jax

            @jax.jit
            def f(x):
                return float(x)
        """))
        sarif = tmp_path / "out.sarif"
        assert lint_main([str(p), "--sarif", str(sarif)]) == 1
        doc = json.loads(sarif.read_text())
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        assert run["tool"]["driver"]["name"] == "paddlelint"
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert {"PT001", "PK101", "PE501", "PE505"} <= rule_ids
        res = run["results"]
        assert res and res[0]["ruleId"] == "PT001"
        assert res[0]["level"] == "error"
        loc = res[0]["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "mod.py"
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["startColumn"] >= 1
        # baselining key rides along for CI dedup across pushes
        assert "paddlelintKey" in res[0]["partialFingerprints"]

    def test_clean_run_writes_empty_results(self, tmp_path, capsys):
        p = tmp_path / "mod.py"
        p.write_text("x = 1\n")
        sarif = tmp_path / "out.sarif"
        assert lint_main([str(p), "--sarif", str(sarif)]) == 0
        doc = json.loads(sarif.read_text())
        assert doc["runs"][0]["results"] == []


# ------------------------------ changed-only fusion-candidate expansion

class TestChangedOnlyFusionExpansion:
    """ISSUE 19 satellite: PE505's legality verdict is a property of a
    fusion PAIR — editing the producer's file must pull the consumer's
    file into a --changed-only selection, or the restricted run would
    re-certify a fusion it can only see half of."""

    PROD = """
        import jax
        from jax.experimental import pallas as pl

        def _oproj_kernel(x_ref, xo_ref, h_ref):
            xo_ref[:] = x_ref[:]
            h_ref[:] = x_ref[:]

        def _oproj_norm_forward(x):
            T, H = x.shape
            bt = 8
            return pl.pallas_call(
                _oproj_kernel,
                grid=(T // bt,),
                in_specs=[pl.BlockSpec((bt, H), lambda i: (i, 0))],
                out_specs=[pl.BlockSpec((bt, H), lambda i: (i, 0)),
                           pl.BlockSpec((bt, H), lambda i: (i, 0))],
                out_shape=[jax.ShapeDtypeStruct(x.shape, x.dtype),
                           jax.ShapeDtypeStruct(x.shape, x.dtype)],
            )(x)
    """
    CONS = """
        import jax
        from jax.experimental import pallas as pl

        def _ffn_kernel(h_ref, o_ref):
            o_ref[:] = h_ref[:]

        def _ffn_forward(h2):
            T, H = h2.shape
            bt = 8
            return pl.pallas_call(
                _ffn_kernel,
                grid=(T // bt,),
                in_specs=[pl.BlockSpec((bt, H), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((bt, H), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(h2.shape, h2.dtype),
            )(h2)
    """

    def _pkg(self, tmp_path):
        from paddle_tpu.analysis.runner import discover
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "prod.py").write_text(textwrap.dedent(self.PROD))
        (pkg / "cons.py").write_text(textwrap.dedent(self.CONS))
        (pkg / "other.py").write_text("x = 1\n")
        return pkg, discover(str(pkg))

    def test_producer_change_pulls_in_consumer_file(self, tmp_path):
        from paddle_tpu.analysis.runner import (
            expand_changed_with_fusion)
        pkg, files = self._pkg(tmp_path)
        changed = {os.path.abspath(str(pkg / "prod.py"))}
        sel = expand_changed_with_fusion(files, changed)
        assert sorted(t[2] for t in sel) == ["pkg/cons.py",
                                             "pkg/prod.py"]

    def test_consumer_change_pulls_in_producer_file(self, tmp_path):
        from paddle_tpu.analysis.runner import (
            expand_changed_with_fusion)
        pkg, files = self._pkg(tmp_path)
        changed = {os.path.abspath(str(pkg / "cons.py"))}
        sel = expand_changed_with_fusion(files, changed)
        assert sorted(t[2] for t in sel) == ["pkg/cons.py",
                                             "pkg/prod.py"]

    def test_unrelated_change_stays_narrow(self, tmp_path):
        from paddle_tpu.analysis.runner import (
            expand_changed_with_fusion)
        pkg, files = self._pkg(tmp_path)
        changed = {os.path.abspath(str(pkg / "other.py"))}
        sel = expand_changed_with_fusion(files, changed)
        assert sorted(t[2] for t in sel) == ["pkg/other.py"]
