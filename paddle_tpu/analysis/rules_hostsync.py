"""PT003: host synchronization inside a hot path.

A "hot path" is anything reachable (through the call graph) from the
configured hot entry points — the trainer step/loop, the generation step
bodies, and the serving predictor (``Config.hot_entry_patterns``). Inside
that region, every ``block_until_ready()``, ``jax.device_get()``,
``.item()``, ``.numpy()``, ``.tolist()`` and ``np.asarray(device_array)``
stalls the Python thread until the device catches up, serializing the
dispatch pipeline — the classic decode-loop throughput killer.

Severity is ``warning``: some syncs are deliberate (fetching the loss once
per logging interval). Those get a baseline entry or an inline
``# paddlelint: disable=PT003`` with a justification.
"""

from __future__ import annotations

import ast
import re
from typing import List, Set

from .callgraph import PackageIndex, _dotted, _last_name, walk_shallow
from .model import Config, Finding, register_rule

register_rule("PT003", "host sync (block_until_ready/device_get/.item/"
                       ".numpy) in a hot path", severity="warning", module=__name__)

_SYNC_METHODS = {"block_until_ready", "item", "numpy", "tolist",
                 "copy_to_host_async"}
_SYNC_FUNCS = {"device_get", "block_until_ready"}
_NP_FUNCS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}


def hot_entries(index: PackageIndex, cfg: Config) -> Set[str]:
    pats = [re.compile(p) for p in cfg.hot_entry_patterns]
    out: Set[str] = set()
    for key in index.functions:
        if any(p.search(key) for p in pats):
            out.add(key)
    return out


def run(index: PackageIndex, cfg: Config) -> List[Finding]:
    if not cfg.wants("PT003"):
        return []
    findings: List[Finding] = []
    region = index.reachable_from(hot_entries(index, cfg))
    for key in sorted(region):
        fi = index.functions.get(key)
        if fi is None:
            continue
        mi = index.modules[fi.modname]
        nodes = (ast.walk(fi.node.body) if isinstance(fi.node, ast.Lambda)
                 else walk_shallow(fi.node))
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            name = _last_name(node.func)
            dotted = _dotted(node.func) or ""
            hit = None
            if isinstance(node.func, ast.Attribute) \
                    and name in _SYNC_METHODS and not node.args:
                hit = f".{name}()"
            elif name in _SYNC_FUNCS and (
                    isinstance(node.func, ast.Name)
                    or dotted.startswith(("jax.", "api."))):
                hit = f"{name}()"
            elif dotted in _NP_FUNCS and node.args:
                hit = f"{dotted}()"
            if hit is None:
                continue
            try:
                frag = " ".join(ast.unparse(node).split())[:48]
            except Exception:  # pragma: no cover
                frag = hit
            findings.append(Finding(
                "PT003", "warning", mi.rel, node.lineno, node.col_offset,
                fi.qualname,
                f"host sync `{hit}` on a hot path (reachable from a "
                f"trainer/generation/serving entry)",
                hint="batch the fetch outside the step, or make it "
                     "conditional on the logging interval",
                detail=f"sync:{hit}:{frag}"))
    return findings
