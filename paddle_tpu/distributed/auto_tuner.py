"""Parallel-config auto-tuner (ref: python/paddle/distributed/auto_tuner/ —
SURVEY §2.3 P12: grid/pruned search over {dp, mp, pp, sharding degree/stage,
micro-batch, recompute}, launching short trials, recording throughput/OOM,
picking the best).

TPU-native: candidates are mesh-degree dicts validated against the device
count and model divisibility; trials run a user-supplied `trial_fn(cfg)`
(typically: build the hybrid mesh, jit one train step on tiny shapes, return
tokens/sec — on hardware, a short timed run; in CI, the simulated mesh)."""

from __future__ import annotations

import itertools
import math
from typing import Callable, Dict, List, Optional

__all__ = ["AutoTuner", "default_search_space", "prune_candidates"]


def default_search_space(total_devices: int) -> Dict[str, List]:
    degrees = [d for d in (1, 2, 4, 8, 16, 32, 64)
               if d <= total_devices]
    return {
        "dp_degree": degrees,
        "mp_degree": degrees,
        "pp_degree": degrees,
        "sharding_degree": degrees,
        "sharding_stage": [1, 2, 3],
        "micro_batch_size": [1, 2, 4, 8],
        "use_recompute": [False, True],
    }


def prune_candidates(space: Dict[str, List], total_devices: int,
                     global_batch: Optional[int] = None,
                     num_layers: Optional[int] = None,
                     num_heads: Optional[int] = None) -> List[Dict]:
    """Cartesian product pruned by the reference's feasibility rules:
    product of mesh degrees == device count; pp divides layers; mp divides
    heads; micro-batch divides per-dp batch."""
    keys = list(space.keys())
    out = []
    for combo in itertools.product(*space.values()):
        cfg = dict(zip(keys, combo))
        prod = (cfg.get("dp_degree", 1) * cfg.get("mp_degree", 1)
                * cfg.get("pp_degree", 1) * cfg.get("sharding_degree", 1))
        if prod != total_devices:
            continue
        if num_layers and num_layers % cfg.get("pp_degree", 1):
            continue
        if num_heads and num_heads % cfg.get("mp_degree", 1):
            continue
        if global_batch:
            dp = cfg.get("dp_degree", 1) * cfg.get("sharding_degree", 1)
            if global_batch % dp:
                continue
            per_dp = global_batch // dp
            if per_dp % cfg.get("micro_batch_size", 1):
                continue
        # dedupe sharding_stage for sharding_degree == 1
        if cfg.get("sharding_degree", 1) == 1 and \
                cfg.get("sharding_stage", 1) != 1:
            continue
        out.append(cfg)
    return out


class AutoTuner:
    """ref CLI: --auto_tuner_json {search space, metric}; here a library:

        tuner = AutoTuner(total_devices=8, global_batch=32, num_layers=12)
        best, history = tuner.tune(trial_fn, max_trials=20)

    trial_fn(cfg) -> throughput (higher better); raise MemoryError (or any
    exception) to mark the config OOM/failed — recorded, not fatal."""

    def __init__(self, total_devices: int, search_space: Optional[Dict] = None,
                 global_batch: Optional[int] = None,
                 num_layers: Optional[int] = None,
                 num_heads: Optional[int] = None, mode: str = "grid"):
        self.total_devices = total_devices
        space = search_space or default_search_space(total_devices)
        self.candidates = prune_candidates(space, total_devices,
                                           global_batch, num_layers,
                                           num_heads)
        if mode == "pruned":
            # heuristic order (ref prune rules): prefer less pp, then less
            # mp (intra-layer comm), then more sharding
            self.candidates.sort(key=lambda c: (
                c.get("pp_degree", 1), c.get("mp_degree", 1),
                -c.get("sharding_degree", 1)))

    def tune(self, trial_fn: Callable[[Dict], float],
             max_trials: Optional[int] = None):
        history = []
        best, best_metric = None, -math.inf
        for cfg in self.candidates[:max_trials]:
            try:
                metric = float(trial_fn(cfg))
                status = "ok"
            except Exception as e:  # OOM / invalid → record and continue
                metric, status = -math.inf, f"failed: {type(e).__name__}"
            history.append({**cfg, "metric": metric, "status": status})
            if metric > best_metric:
                best, best_metric = cfg, metric
        return best, history
