"""Launch controllers: pod construction, watch loop, restart policy, elastic.

Reference mechanism (SURVEY §2.3 P14, §5.3):
- python/paddle/distributed/launch/controllers/collective.py — master
  rendezvous (TCPStore/etcd), builds the pod rank table, spawns per-rank
  subprocesses with PADDLE_* env, writes per-rank `workerlog.N`, watches
  children and restarts per policy.
- python/paddle/distributed/fleet/elastic/manager.py — ElasticManager
  watches membership (etcd TTL keys); on join/leave kills local trainers
  and relaunches with regenerated rank env.

TPU-native rework: the rendezvous/heartbeat store is our C++ TCPStore
(paddle_tpu.native); per-host processes get both the PADDLE_* env vars and
the jax.distributed coordination vars (COORDINATOR_ADDRESS / process id) so
`init_parallel_env()` can call jax.distributed.initialize on pods. Failure
detection = child exit codes + store heartbeats; recovery = checkpoint-based
relaunch (SURVEY §5.3: the TPU-idiomatic elastic story is preemption-aware
checkpoint + restart, not in-flight reconstruction).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

from ...native import TCPStore

__all__ = ["CollectiveController", "ElasticManager"]


class _Proc:
    def __init__(self, popen, rank, log_path, log_file):
        self.popen = popen
        self.rank = rank
        self.log_path = log_path
        self.log_file = log_file


class CollectiveController:
    """Spawn + watch the local ranks of a collective job."""

    def __init__(self, args):
        self.args = args
        self.node_rank = int(args.node_rank)
        self.nnodes = int(str(args.nnodes).split(":")[0])
        self.nproc = int(args.nproc_per_node)
        self.world_size = self.nnodes * self.nproc
        self.procs: List[_Proc] = []
        self.store: Optional[TCPStore] = None
        self._restarts = 0

    # -- rendezvous ----------------------------------------------------------
    def _master_hostport(self):
        if self.args.master:
            host, _, port = self.args.master.rpartition(":")
            return host or "127.0.0.1", int(port)
        return "127.0.0.1", 0

    def rendezvous(self):
        host, port = self._master_hostport()
        is_master = self.node_rank == 0
        self.store = TCPStore(host=host, port=port, is_master=is_master,
                              world_size=self.nnodes,
                              timeout=self.args.rdzv_timeout)
        if is_master:
            port = self.store.port
        self.master_endpoint = f"{host}:{port}"
        # publish this node, wait for everyone (ref: pod/rank table build)
        self.store.set(f"node/{self.node_rank}", os.uname().nodename)
        self.store.barrier("rendezvous", timeout=self.args.rdzv_timeout)

    # -- env -----------------------------------------------------------------
    def _rank_env(self, local_rank: int) -> dict:
        rank = self.node_rank * self.nproc + local_rank
        endpoints = ",".join(
            f"{self.master_endpoint.split(':')[0]}:{9000 + r}"
            for r in range(self.world_size))
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(self.world_size),
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_CURRENT_ENDPOINT":
                f"{self.master_endpoint.split(':')[0]}:{9000 + rank}",
            "PADDLE_MASTER": self.master_endpoint,
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_NNODES": str(self.nnodes),
            # jax.distributed bridge (multi-host TPU bring-up): a separate
            # port from the rendezvous store (see _publish_jax_coordinator;
            # AttributeError here means spawn() ordering broke — fail fast)
            "COORDINATOR_ADDRESS": self.jax_coordinator,
            "JAX_PROCESS_ID": str(rank),
            "JAX_NUM_PROCESSES": str(self.world_size),
        })
        if self.args.devices:
            env["TPU_VISIBLE_DEVICES"] = self.args.devices
        return env

    # -- spawn / watch -------------------------------------------------------
    def _publish_jax_coordinator(self):
        """Pick + publish the jax coordination-service endpoint (its OWN
        port — the store already owns master_endpoint's). Called at spawn
        time, not rendezvous, to shrink the free-port TOCTOU window to the
        child's startup; the port is drawn BELOW the Linux ephemeral range
        (32768+) so workers' own outbound connections can't land on it."""
        import random
        import socket
        host = self.master_endpoint.split(":")[0]
        if self.node_rank == 0:
            rnd = random.Random()
            jport = None
            for _ in range(64):
                cand = rnd.randrange(20000, 30000)
                s = socket.socket()
                try:
                    s.bind((host if host != "127.0.0.1" else "", cand))
                    jport = cand
                    break
                except OSError:
                    continue
                finally:
                    s.close()
            if jport is None:
                raise RuntimeError("no free port for the jax coordinator")
            self.store.set("jax/coordinator", f"{host}:{jport}")
        self.jax_coordinator = self.store.wait(
            "jax/coordinator", timeout=self.args.rdzv_timeout).decode()

    def spawn(self):
        if not hasattr(self, "jax_coordinator"):
            self._publish_jax_coordinator()
        os.makedirs(self.args.log_dir, exist_ok=True)
        self.procs = []
        for lr in range(self.nproc):
            rank = self.node_rank * self.nproc + lr
            log_path = os.path.join(self.args.log_dir, f"workerlog.{rank}")
            logf = open(log_path, "ab", buffering=0)
            cmd = [sys.executable, "-u", self.args.training_script,
                   *self.args.training_script_args]
            p = subprocess.Popen(cmd, env=self._rank_env(lr), stdout=logf,
                                 stderr=subprocess.STDOUT)
            self.procs.append(_Proc(p, rank, log_path, logf))

    def _kill_all(self, sig=signal.SIGTERM, grace: float = 5.0):
        for pr in self.procs:
            if pr.popen.poll() is None:
                pr.popen.send_signal(sig)
        deadline = time.time() + grace
        for pr in self.procs:
            left = max(0.1, deadline - time.time())
            try:
                pr.popen.wait(timeout=left)
            except subprocess.TimeoutExpired:
                pr.popen.kill()
        for pr in self.procs:
            pr.log_file.close()

    def watch(self) -> int:
        """Poll children; on failure either restart the pod (up to
        --max_restarts) or tear down and propagate the exit code."""
        while True:
            alive = 0
            restarted = False
            for pr in self.procs:
                rc = pr.popen.poll()
                if rc is None:
                    alive += 1
                elif rc != 0:
                    if self._restarts < self.args.max_restarts:
                        self._restarts += 1
                        self._kill_all()
                        self.spawn()
                        restarted = True
                        break
                    self._kill_all()
                    return rc
            if restarted:
                continue
            if alive == 0:
                for pr in self.procs:
                    pr.log_file.close()
                return 0
            time.sleep(self.args.poll_interval)

    def run(self) -> int:
        self.rendezvous()
        self.spawn()
        try:
            return self.watch()
        finally:
            if self.store is not None:
                self.store.close()


class ElasticManager:
    """Membership watcher (ref: ElasticManager over etcd): nodes heartbeat
    TTL keys in the store; scale events trigger relaunch with new ranks."""

    def __init__(self, store: TCPStore, node_rank: int, ttl: float = 10.0):
        self.store = store
        self.node_rank = node_rank
        self.ttl = ttl
        self._stop = False

    def heartbeat(self) -> None:
        self.store.set(f"heartbeat/{self.node_rank}", str(time.time()))

    def alive_nodes(self, nnodes: int) -> List[int]:
        now = time.time()
        out = []
        for i in range(nnodes):
            v = self.store.get(f"heartbeat/{i}")
            if v is not None and now - float(v) < self.ttl:
                out.append(i)
        return out

    def membership_changed(self, expected: int) -> bool:
        return len(self.alive_nodes(expected)) != expected

    def regenerate_ranks(self, nnodes: int) -> dict:
        """Compacted old-rank -> new-rank map over the surviving members
        (ref: ElasticManager's rank regeneration on a scale-in event). The
        relaunch then re-runs the launcher with nnodes=len(map) and each
        survivor's new node_rank."""
        alive = sorted(self.alive_nodes(nnodes))
        return {old: new for new, old in enumerate(alive)}
