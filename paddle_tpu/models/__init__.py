"""Model zoo (capability parity with the ecosystem models the baseline
configs exercise — SURVEY §2.4: BERT, Llama, ERNIE-style, MoE decoders,
PP-OCR CNNs). Models are written against paddle_tpu.nn and are trace-ready."""

from . import bert  # noqa: F401
from . import deepseek  # noqa: F401
from . import gpt  # noqa: F401
from . import llama  # noqa: F401
from . import moe_llm  # noqa: F401
from . import qwen2  # noqa: F401
