"""Native C++ components: flags, TCPStore, profiler (SURVEY §2.1 native
contract). The store is exercised cross-process via subprocess clients."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu import native


def test_native_lib_builds():
    assert native.available(), "native.so failed to build (g++ required)"


def test_flags_roundtrip_and_env_override(monkeypatch):
    native.native_flag_define("FLAGS_test_native_x", "7")
    assert native.native_flag_get("FLAGS_test_native_x") == "7"
    native.native_flag_set("FLAGS_test_native_x", "9")
    assert native.native_flag_get("FLAGS_test_native_x") == "9"
    monkeypatch.setenv("FLAGS_test_native_env", "42")
    native.native_flag_define("FLAGS_test_native_env", "0")
    assert native.native_flag_get("FLAGS_test_native_env") == "42"


class TestTCPStore:
    def test_kv_set_get_add(self):
        s = native.TCPStore(is_master=True, world_size=1)
        try:
            s.set("k", "v1")
            assert s.get("k") == b"v1"
            assert s.get("missing") is None
            assert s.add("ctr", 5) == 5
            assert s.add("ctr", 2) == 7
            s.delete("k")
            assert s.get("k") is None
        finally:
            s.close()

    def test_wait_blocks_until_set(self):
        import threading
        s = native.TCPStore(is_master=True, world_size=1)
        c = native.TCPStore(port=s.port, world_size=1)
        try:
            def setter():
                import time
                time.sleep(0.2)
                c.set("late", "here")
            t = threading.Thread(target=setter)
            t.start()
            assert s.wait("late", timeout=5.0) == b"here"
            t.join()
        finally:
            c.close()
            s.close()

    def test_wait_timeout(self):
        s = native.TCPStore(is_master=True, world_size=1)
        try:
            with pytest.raises(TimeoutError):
                s.wait("never", timeout=0.3)
        finally:
            s.close()

    def test_cross_process_barrier(self, tmp_path):
        """3 real OS processes rendezvous through the C++ store."""
        s = native.TCPStore(is_master=True, world_size=4)
        # load the native module standalone: the subprocess must not import
        # the full framework (the axon site hook would try to claim the
        # single TPU and block behind the parent's claim)
        native_init = os.path.join(os.getcwd(), "paddle_tpu", "native",
                                   "__init__.py")
        script = textwrap.dedent(f"""
            import importlib.util
            spec = importlib.util.spec_from_file_location(
                "pt_native", {repr(native_init)})
            native = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(native)
            c = native.TCPStore(port={s.port}, world_size=4)
            c.add("joined", 1)
            c.barrier("b0", timeout=30)
            print("OK")
        """)
        procs = [subprocess.Popen([sys.executable, "-c", script],
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.PIPE)
                 for _ in range(3)]
        s.barrier("b0", timeout=30)
        for p in procs:
            out, err = p.communicate(timeout=60)
            assert p.returncode == 0, err.decode()
            assert b"OK" in out
        assert int(s.get("joined")) == 3
        s.close()


class TestProfiler:
    def test_record_and_export(self, tmp_path):
        native.prof_clear()
        native.prof_enable(True)
        with native.RecordEvent("outer"):
            with native.RecordEvent("inner"):
                sum(range(1000))
        native.prof_enable(False)
        assert native.prof_event_count() == 2
        out = str(tmp_path / "trace.json")
        n = native.prof_export(out)
        assert n == 2
        data = json.load(open(out))
        names = {e["name"] for e in data["traceEvents"]}
        assert names == {"outer", "inner"}
        assert all(e["ph"] == "X" and e["dur"] >= 0
                   for e in data["traceEvents"])
        native.prof_clear()

    def test_disabled_records_nothing(self):
        native.prof_clear()
        native.prof_enable(False)
        with native.RecordEvent("nope"):
            pass
        assert native.prof_event_count() == 0
