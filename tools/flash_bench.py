"""In-tree flash kernel vs bundled kernel on the local chip (VERDICT r2
item 9 'done' bar: within 5% on the bench shapes, plus coverage the
bundled kernel refuses). Prints a table and writes docs/FLASH_BENCH.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
from bench_util import ab_rounds, band, ratio_band  # noqa: E402


def main():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_flash import flash_sdpa
    from paddle_tpu.ops.flash_attention import (_flash_block_sizes,
                                                sdpa_reference)

    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        print("WARNING: not on TPU; numbers meaningless", file=sys.stderr)
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as bundled)

    rows = []
    # bench shapes: flagship shard attention (4 q-heads d128) and a
    # fatter 8-head case, causal, plus D=64 and unequal-length rows the
    # bundled kernel refuses
    shapes = [
        ("8b_shard_s2048", 4, 2048, 2048, 4, 128, True),
        ("8b_shard_s8192", 1, 8192, 8192, 4, 128, True),
        ("h8_s4096", 2, 4096, 4096, 8, 128, True),
        ("noncausal_s2048", 4, 2048, 2048, 4, 128, False),
        ("D64_s4096", 2, 4096, 4096, 8, 64, True),
        ("cross_causal_1k_to_8k", 1, 1024, 8192, 4, 128, True),  # bundled refuses
    ]
    for name, B, Sq, Sk, H, D, causal in shapes:
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(B, Sq, H, D), jnp.bfloat16)
        k = jnp.asarray(rng.randn(B, Sk, H, D), jnp.bfloat16)
        v = jnp.asarray(rng.randn(B, Sk, H, D), jnp.bfloat16)

        intree_fwd = jax.jit(lambda q, k, v: flash_sdpa(
            q, k, v, causal=causal))

        def loss_intree(q, k, v):
            return jnp.sum(flash_sdpa(q, k, v, causal=causal)
                           .astype(jnp.float32) ** 2)
        g_intree = jax.jit(jax.grad(loss_intree, (0, 1, 2)))

        kernels = {"intree_fwd": (intree_fwd, (q, k, v)),
                   "intree_fwdbwd": (g_intree, (q, k, v))}
        if Sq == Sk or not causal:
            qh, kh, vh = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
            bundled_fwd = jax.jit(lambda qh, kh, vh: bundled(
                qh, kh, vh, causal=causal, sm_scale=D ** -0.5,
                block_sizes=_flash_block_sizes(Sq, Sk)))

            def loss_bundled(qh, kh, vh):
                return jnp.sum(bundled(
                    qh, kh, vh, causal=causal, sm_scale=D ** -0.5,
                    block_sizes=_flash_block_sizes(Sq, Sk))
                    .astype(jnp.float32) ** 2)
            g_bundled = jax.jit(jax.grad(loss_bundled, (0, 1, 2)))
            kernels["bundled_fwd"] = (bundled_fwd, (qh, kh, vh))
            kernels["bundled_fwdbwd"] = (g_bundled, (qh, kh, vh))

        # same-run interleaved rounds (VERDICT r4 item 3): intree and
        # bundled alternate within each round; every ratio carries the
        # per-round band so <5% claims are checkable against the noise
        runs = ab_rounds(kernels, rounds=3, reps=10)

        row = dict(shape=name, B=B, Sq=Sq, Sk=Sk, H=H, D=D, causal=causal,
                   rounds=3,
                   intree_fwd=band(runs["intree_fwd"]),
                   intree_fwdbwd=band(runs["intree_fwdbwd"]),
                   bundled_fwd=(band(runs["bundled_fwd"])
                                if "bundled_fwd" in runs else None),
                   bundled_fwdbwd=(band(runs["bundled_fwdbwd"])
                                   if "bundled_fwdbwd" in runs else None))
        if "bundled_fwd" in runs:
            row["fwd_ratio_intree_over_bundled"] = ratio_band(
                runs["intree_fwd"], runs["bundled_fwd"])
            row["fwdbwd_ratio_intree_over_bundled"] = ratio_band(
                runs["intree_fwdbwd"], runs["bundled_fwdbwd"])
        rows.append(row)
        print(json.dumps(row), flush=True)

    out = os.path.join(os.path.dirname(__file__), "..", "docs",
                       "FLASH_BENCH.json")
    if on_tpu:
        with open(out, "w") as f:
            json.dump(dict(device=str(jax.devices()[0].device_kind),
                           rows=rows), f, indent=2)


if __name__ == "__main__":
    main()
