"""In-tree FlashMask block-skipping kernel (ops/pallas_flashmask.py) —
parity vs the dense-mask composite oracle for every paddle startend
encoding, gradient parity, O(S) memory assertion, skip-map soundness,
and the sdpa routing report (VERDICT r1 item 3; ref: FlashMask variant
of paddle/phi/kernels/gpu/flash_attn_kernel.cu, SURVEY §5.7.1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.flash_attention import (flashmask_attention,
                                            sdpa_path, sdpa_reference)
from paddle_tpu.ops.pallas_flashmask import (bands_from_startend,
                                             flashmask_block_kinds,
                                             flashmask_sdpa)

B, S, H, D = 2, 256, 2, 64


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, S, H, D)),
                             jnp.float32)
    return mk(), mk(), mk()


def _dense_allow(se_np, causal):
    """numpy oracle: dense [B,Hm,S,S] allow mask from the encoding."""
    Bm, Hm, Sk, C = se_np.shape
    rows = np.arange(S)[:, None]
    allow = np.ones((Bm, Hm, S, Sk), bool)
    for b in range(Bm):
        for h in range(Hm):
            if C == 1:
                m = rows >= se_np[b, h, :, 0][None, :]
            elif C == 2 and causal:
                m = ((rows >= se_np[b, h, :, 0][None, :])
                     & (rows < se_np[b, h, :, 1][None, :]))
            elif C == 2:
                m = ((rows >= se_np[b, h, :, 0][None, :])
                     | (rows < se_np[b, h, :, 1][None, :]))
            else:
                m = (((rows >= se_np[b, h, :, 0][None, :])
                      & (rows < se_np[b, h, :, 1][None, :]))
                     | ((rows >= se_np[b, h, :, 2][None, :])
                        & (rows < se_np[b, h, :, 3][None, :])))
            allow[b, h] = ~m
    if causal:
        allow &= (np.arange(S)[None, :] <= rows)
    return allow


def _packed_doc_se():
    """causal C=1 (LTS): three packed documents per batch row."""
    ends = np.zeros((B, 1, S, 1), np.int32)
    for b in range(B):
        cuts = [96, 160, S] if b == 0 else [128, 224, S]
        lo = 0
        for c in cuts:
            ends[b, 0, lo:c, 0] = c
            lo = c
    return ends


CASES = {
    "causal_C1_packed_docs": (_packed_doc_se, True),
    "causal_C2_band": (
        lambda: np.stack([
            np.full((B, 1, S), 80, np.int32),
            np.full((B, 1, S), 200, np.int32)], -1), True),
    "noncausal_C2": (
        lambda: np.stack([
            np.full((B, 1, S), 192, np.int32),
            np.full((B, 1, S), 64, np.int32)], -1), False),
    "noncausal_C4": (
        lambda: np.stack([
            np.full((B, 1, S), 160, np.int32),
            np.full((B, 1, S), 224, np.int32),
            np.full((B, 1, S), 32, np.int32),
            np.full((B, 1, S), 96, np.int32)], -1), False),
}


@pytest.mark.parametrize("name", list(CASES))
def test_kernel_matches_dense_oracle(name):
    mk_se, causal = CASES[name]
    se_np = np.asarray(mk_se())
    q, k, v = _qkv()
    out = flashmask_sdpa(q, k, v, jnp.asarray(se_np), causal=causal)
    allow = _dense_allow(se_np, causal)
    ref = sdpa_reference(q, k, v, mask=jnp.asarray(allow), causal=False)
    valid = allow.any(axis=-1)  # [B,Hm,S] rows with >=1 visible key
    got, refn = np.asarray(out), np.asarray(ref)
    for b in range(B):
        vmask = valid[b, 0]
        np.testing.assert_allclose(got[b][vmask], refn[b][vmask],
                                   rtol=3e-5, atol=3e-5, err_msg=name)
    # fully-masked rows are exactly zero from the kernel (documented)
    if not valid.all():
        empty = ~valid[0, 0]
        np.testing.assert_allclose(got[0][empty], 0.0, atol=1e-6)


def test_kernel_gradients_match_oracle():
    se_np = np.asarray(_packed_doc_se())
    q, k, v = _qkv(3)
    allow = _dense_allow(se_np, True)
    valid = jnp.asarray(allow.any(axis=-1)[:, 0], jnp.float32)

    def loss_kernel(q_, k_, v_):
        o = flashmask_sdpa(q_, k_, v_, jnp.asarray(se_np), causal=True)
        return (o * valid[:, :, None, None]).sum()

    def loss_ref(q_, k_, v_):
        o = sdpa_reference(q_, k_, v_, mask=jnp.asarray(allow),
                           causal=False)
        return (o * valid[:, :, None, None]).sum()

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_, nm in zip(gk, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=3e-4, atol=3e-4, err_msg=nm)


def test_block_kinds_sound_and_skipping():
    """kind==0 blocks must be fully masked in the dense oracle
    (soundness), and a packed-doc mask must actually skip a meaningful
    fraction beyond the causal triangle (the FlashMask point)."""
    se_np = np.asarray(_packed_doc_se())
    bands = bands_from_startend(jnp.asarray(se_np), S, S, True)
    kinds = np.asarray(flashmask_block_kinds(bands, S, S, 128, 128, True))
    allow = _dense_allow(se_np, True)
    nq = nk = S // 128
    for b in range(B):
        for qi in range(nq):
            for kj in range(nk):
                blk = allow[b, 0, qi * 128:(qi + 1) * 128,
                            kj * 128:(kj + 1) * 128]
                if kinds[b, 0, qi, kj] == 0:
                    assert not blk.any(), (b, qi, kj)
    # causal triangle alone keeps nq*(nq+1)/2 blocks; packed docs must
    # skip at least one more (the cross-document block)
    kept = kinds[:, 0].sum(axis=(1, 2))
    assert (kept < nq * (nq + 1) // 2).any(), kinds


def test_no_dense_mask_materialized():
    """THE FlashMask memory contract: no [.., Sq, Sk] buffer anywhere in
    the kernel-path jaxpr (the dense mask exists only as [bq, bk] tiles
    inside the pallas kernel)."""
    se = jnp.asarray(_packed_doc_se())
    q, k, v = _qkv()

    def run(q_, k_, v_):
        return flashmask_sdpa(q_, k_, v_, se, causal=True)

    jaxpr = jax.make_jaxpr(run)(q, k, v)

    def walk(jx):
        for eqn in jx.eqns:
            for av in list(eqn.invars) + list(eqn.outvars):
                aval = getattr(av, "aval", None)
                if aval is not None and len(aval.shape) >= 2:
                    assert not (aval.shape[-2:] == (S, S)), \
                        f"dense [.., {S}, {S}] buffer: {eqn.primitive}"
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    walk(sub.jaxpr)
    walk(jaxpr.jaxpr)


def test_flashmask_attention_routes_to_kernel():
    """The public API must hit the kernel for block-divisible shapes and
    the composite otherwise (shape 100 is not 128-divisible)."""
    se = jnp.asarray(_packed_doc_se())
    q, k, v = _qkv()
    out, _ = flashmask_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                                 paddle.to_tensor(v),
                                 paddle.to_tensor(se), causal=True)
    assert tuple(out.shape) == (B, S, H, D)
    q2 = paddle.to_tensor(np.asarray(q)[:, :100])
    k2 = paddle.to_tensor(np.asarray(k)[:, :100])
    v2 = paddle.to_tensor(np.asarray(v)[:, :100])
    se2 = paddle.to_tensor(np.asarray(se)[:, :, :100])
    out2, _ = flashmask_attention(q2, k2, v2, se2, causal=True)
    assert tuple(out2.shape) == (B, 100, H, D)


class TestSdpaRouting:
    def test_padding_mask_routes_to_segmented(self):
        q, k, v = _qkv()
        pad = np.ones((B, S), bool)
        pad[:, 200:] = False
        # off-TPU the gate reports composite; the ROUTING decision is
        # what we assert, so emulate eligibility via the path fn inputs
        path = sdpa_path(q, k, mask=jnp.asarray(pad), causal=True)
        if jax.default_backend() == "tpu":
            assert path == "flash_segmented"
        else:
            assert path == "composite"

    def test_dense_mask_and_dropout_stay_composite(self):
        q, k, v = _qkv()
        m = jnp.ones((B, 1, S, S), bool)
        assert sdpa_path(q, k, mask=m, causal=True) == "composite"
        assert sdpa_path(q, k, dropout_p=0.1) == "composite"

    def test_padding_mask_values_match_composite_on_valid_rows(self):
        from paddle_tpu.ops.flash_attention import sdpa
        q, k, v = _qkv(5)
        pad_np = np.ones((B, S), bool)
        pad_np[:, 192:] = False
        pad = jnp.asarray(pad_np)
        got = np.asarray(sdpa(q, k, v, mask=pad, causal=True))
        ref = np.asarray(sdpa_reference(
            q, k, v, mask=pad[:, None, None, :], causal=True))
        np.testing.assert_allclose(got[:, :192], ref[:, :192],
                                   rtol=3e-5, atol=3e-5)
