"""paddle_tpu.nn (ref surface: python/paddle/nn/)."""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer.layers import (Layer, LayerDict, LayerList, Parameter,  # noqa: F401
                           ParameterList, Sequential)
from .layer.common import (AdaptiveAvgPool1D, AdaptiveAvgPool2D,  # noqa: F401
                           AlphaDropout, AvgPool1D, AvgPool2D, Bilinear,
                           Conv1D, Conv2D, Conv2DTranspose, Conv3D,
                           CosineSimilarity, Dropout, Dropout2D, Embedding,
                           Flatten, Identity, Linear, MaxPool1D, MaxPool2D,
                           Pad1D, Pad2D, Pad3D, PixelShuffle, Upsample,
                           UpsamplingBilinear2D, UpsamplingNearest2D)
from .layer.norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,  # noqa: F401
                         GroupNorm, InstanceNorm2D, LayerNorm,
                         LocalResponseNorm, RMSNorm, SyncBatchNorm)
from .layer.activation import (CELU, ELU, GELU, GLU, SELU, Hardshrink,  # noqa: F401
                               Hardsigmoid, Hardswish, Hardtanh, LeakyReLU,
                               LogSoftmax, Mish, PReLU, ReLU, ReLU6, Sigmoid,
                               SiLU, Softmax, Softplus, Softshrink, Softsign,
                               Swish, Tanh, Tanhshrink, ThresholdedReLU)
from .layer.rnn import (GRU, GRUCell, LSTM, LSTMCell, RNN,  # noqa: F401
                        SimpleRNN, SimpleRNNCell)
from .layer.transformer import (MultiHeadAttention, Transformer,  # noqa: F401
                                TransformerDecoder, TransformerDecoderLayer,
                                TransformerEncoder, TransformerEncoderLayer)
from .layer.loss import (BCELoss, BCEWithLogitsLoss, CosineEmbeddingLoss,  # noqa: F401
                         CrossEntropyLoss, CTCLoss, HingeEmbeddingLoss,
                         KLDivLoss, L1Loss, MarginRankingLoss, MSELoss,
                         NLLLoss, SmoothL1Loss)
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
