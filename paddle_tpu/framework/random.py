"""Global RNG state with paddle-parity stateful surface over JAX PRNG keys.

Ref surface: paddle.seed, paddle.get_rng_state/set_rng_state (python/paddle/
framework/random.py upstream layout). Mechanism is TPU-native: a counter-based
threefry key, advanced by fold_in per draw — deterministic, checkpointable,
and per-mesh-axis foldable (the TP RNGStatesTracker parity lives in
paddle_tpu.distributed.random, built on the same fold_in primitive).

Inside a traced function (jit), eager draws would bake constants; traced code
paths (Trainer, dropout under to_static) must push an explicit traced key via
:func:`rng_key_guard`, which takes precedence over the global generator.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import jax
import jax.numpy as jnp

__all__ = ["Generator", "seed", "default_generator", "next_key",
           "get_rng_state", "set_rng_state", "rng_key_guard", "fold_in_axis"]


class Generator:
    def __init__(self, seed_: int = 0):
        self.manual_seed(seed_)

    def manual_seed(self, s: int) -> "Generator":
        self._seed = int(s)
        self._counter = 0
        self._key = jax.random.key(int(s))
        return self

    def next_key(self):
        self._counter += 1
        return jax.random.fold_in(self._key, self._counter)

    def get_state(self):
        return (self._seed, self._counter)

    def set_state(self, state) -> None:
        self._seed, self._counter = int(state[0]), int(state[1])
        self._key = jax.random.key(self._seed)


default_generator = Generator(0)


class _TraceState(threading.local):
    def __init__(self):
        self.key_stack: List = []
        self.trace_counter = 0


_trace = _TraceState()


class rng_key_guard:
    """Push an explicit (possibly traced) base key; draws inside the context
    fold a local counter into it instead of touching global state."""

    def __init__(self, key):
        if isinstance(key, int):
            key = jax.random.key(key)
        self._key = key

    def __enter__(self):
        _trace.key_stack.append([self._key, 0])
        return self

    def __exit__(self, *exc):
        _trace.key_stack.pop()
        return False


def next_key():
    if _trace.key_stack:
        entry = _trace.key_stack[-1]
        entry[1] += 1
        return jax.random.fold_in(entry[0], entry[1])
    return default_generator.next_key()


def in_rng_guard() -> bool:
    return bool(_trace.key_stack)


def seed(s: int) -> Generator:
    """paddle.seed parity: reseed the global generator."""
    return default_generator.manual_seed(s)


def get_rng_state():
    return [default_generator.get_state()]


def set_rng_state(state) -> None:
    default_generator.set_state(state[0])


def fold_in_axis(key, axis_index):
    """Fold a mesh-axis index into a key — the TPU-native mechanism behind
    deterministic per-rank dropout (ref parity: fleet RNGStatesTracker,
    meta_parallel/random.py `get_rng_state_tracker`)."""
    return jax.random.fold_in(key, axis_index)
