"""DeepSeek-V2 family: MLA (multi-head latent attention) + MoE FFN.

Reference capability: PaddleNLP paddlenlp/transformers/deepseek_v2/
modeling.py (SURVEY §2.4 — DeepSeekMoE baseline row). The defining feature
over the Qwen2-MoE pattern (models/moe_llm.py) is MLA: queries and KV are
low-rank compressed (q_lora_rank / kv_lora_rank) and position information
travels in a small decoupled rope sub-head — a single shared k_pe head
(MQA-style) plus per-head q_pe — so the KV cache is the compressed latent
instead of full K/V.

TPU-first notes: the compressions are small dense matmuls (MXU-friendly);
the decoupled-rope concat keeps the big nope dims rope-free so XLA fuses
the kv_b expansion into the attention einsum; attention math is einsum-based
because q/k head dim (nope+rope) differs from the v head dim — the flash
kernel path applies when they match.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..distributed.parallel_layers import MP_AXIS, ParallelCrossEntropy
from .llama import LlamaMLP, apply_rope, precompute_rope
from .moe_llm import MoEConfig
from ..incubate.moe import MoELayer

__all__ = ["DeepSeekV2Config", "MLAttention", "DeepSeekV2DecoderLayer",
           "DeepSeekV2Model", "DeepSeekV2ForCausalLM",
           "deepseek_v2_tiny_config"]


class DeepSeekV2Config(MoEConfig):
    def __init__(self, q_lora_rank=None, kv_lora_rank=512,
                 qk_nope_head_dim=128, qk_rope_head_dim=64,
                 v_head_dim=128, **kw):
        super().__init__(**kw)
        self.q_lora_rank = q_lora_rank
        self.kv_lora_rank = kv_lora_rank
        self.qk_nope_head_dim = qk_nope_head_dim
        self.qk_rope_head_dim = qk_rope_head_dim
        self.v_head_dim = v_head_dim
        self.qk_head_dim = qk_nope_head_dim + qk_rope_head_dim


def deepseek_v2_tiny_config(**kw) -> DeepSeekV2Config:
    base = dict(vocab_size=512, hidden_size=64, num_hidden_layers=2,
                num_attention_heads=4, num_key_value_heads=4,
                intermediate_size=128, max_position_embeddings=64,
                q_lora_rank=32, kv_lora_rank=16, qk_nope_head_dim=16,
                qk_rope_head_dim=8, v_head_dim=16,
                num_experts=4, top_k=2, moe_intermediate_size=32,
                shared_expert_intermediate_size=32,
                first_k_dense_replace=1)
    base.update(kw)
    return DeepSeekV2Config(**base)


def _linear(in_f, out_f, spec=None):
    l = nn.Linear(in_f, out_f, bias_attr=False)
    if spec is not None:
        l.weight._sharding_spec = spec
    return l


class MLAttention(nn.Layer):
    """Multi-head latent attention (DeepSeek-V2).

    x → [q_a → RMSNorm → q_b]              per-head (nope ‖ rope) queries
    x → kv_a → (c_kv ‖ k_pe)               latent + shared rope key head
        c_kv → RMSNorm → kv_b              per-head (k_nope ‖ v)
    attn over (nope ‖ rope) q·k, value dim v_head_dim, then o_proj.
    """

    def __init__(self, c: DeepSeekV2Config):
        super().__init__()
        self.c = c
        nh = c.num_attention_heads
        dn, dr, dv = c.qk_nope_head_dim, c.qk_rope_head_dim, c.v_head_dim
        if c.q_lora_rank:
            self.q_a_proj = _linear(c.hidden_size, c.q_lora_rank)
            self.q_a_layernorm = nn.RMSNorm(c.q_lora_rank, c.rms_norm_eps)
            self.q_b_proj = _linear(c.q_lora_rank, nh * (dn + dr),
                                    P(None, MP_AXIS))
        else:
            self.q_proj = _linear(c.hidden_size, nh * (dn + dr),
                                  P(None, MP_AXIS))
        self.kv_a_proj_with_mqa = _linear(c.hidden_size,
                                          c.kv_lora_rank + dr)
        self.kv_a_layernorm = nn.RMSNorm(c.kv_lora_rank, c.rms_norm_eps)
        self.kv_b_proj = _linear(c.kv_lora_rank, nh * (dn + dv),
                                 P(None, MP_AXIS))
        self.o_proj = _linear(nh * dv, c.hidden_size, P(MP_AXIS, None))

    def forward(self, x, cos, sin, attn_mask=None):
        c = self.c
        B, S, _ = x.shape
        nh = c.num_attention_heads
        dn, dr, dv = c.qk_nope_head_dim, c.qk_rope_head_dim, c.v_head_dim
        eps = c.rms_norm_eps
        mask = attn_mask._data if isinstance(attn_mask, Tensor) else attn_mask
        from ..core.dispatch import apply as _apply

        def _rms(h, w):
            var = jnp.mean(jnp.square(h.astype(jnp.float32)), -1,
                           keepdims=True)
            return (h * jax.lax.rsqrt(var + eps).astype(h.dtype)) * w

        # the whole latent-attention computation runs inside ONE dispatch
        # apply so the tape sees every projection weight (the llama.py
        # convention — raw-array math outside apply would be invisible to
        # autograd)
        def impl(h, w_kv_a, g_kv, w_kv_b, w_o, *q_weights):
            if c.q_lora_rank:
                w_q_a, g_q, w_q_b = q_weights
                q = _rms(h @ w_q_a, g_q) @ w_q_b
            else:
                (w_q,) = q_weights
                q = h @ w_q
            q = q.reshape(B, S, nh, dn + dr)
            q_nope, q_pe = q[..., :dn], q[..., dn:]

            kv_a = h @ w_kv_a
            c_kv, k_pe = kv_a[..., :c.kv_lora_rank], \
                kv_a[..., c.kv_lora_rank:]
            kv = (_rms(c_kv, g_kv) @ w_kv_b).reshape(B, S, nh, dn + dv)
            k_nope, v = kv[..., :dn], kv[..., dn:]

            q_pe = apply_rope(q_pe, cos, sin)
            k_pe = apply_rope(k_pe[:, :, None, :], cos, sin)
            k_pe = jnp.broadcast_to(k_pe, (B, S, nh, dr))

            qh = jnp.concatenate([q_nope, q_pe], -1)
            kh = jnp.concatenate([k_nope, k_pe], -1)

            if c.use_flash_attention and mask is None:
                if dv == dn + dr:
                    from ..ops.flash_attention import sdpa
                    o = sdpa(qh, kh, v, causal=True)
                else:
                    # real DeepSeek geometry (dv != dn+dr, e.g. 128 vs
                    # 192): zero-pad heads to the lane so the O(S) flash
                    # route applies — the dense path below OOMs
                    # long-context prefill on [B,nh,S,S] f32 scores
                    from ..ops.flash_attention import sdpa_padded_heads
                    o = sdpa_padded_heads(
                        qh, kh, v, causal=True,
                        scale=float(dn + dr) ** -0.5)
            else:
                scale = 1.0 / float(jnp.sqrt(jnp.float32(dn + dr)))
                scores = jnp.einsum("bsnd,btnd->bnst", qh, kh) * scale
                scores = scores.astype(jnp.float32)
                causal = jnp.tril(jnp.ones((S, S), bool))
                neg = jnp.asarray(-1e30, scores.dtype)
                scores = jnp.where(causal[None, None], scores, neg)
                if mask is not None:  # compose, never replace (gpt.py conv.)
                    m = jnp.asarray(mask)
                    if m.dtype == jnp.bool_:
                        scores = jnp.where(m, scores, neg)
                    else:
                        scores = scores + m.astype(scores.dtype)
                w = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
                o = jnp.einsum("bnst,btnv->bsnv", w, v)
            return o.reshape(B, S, nh * dv) @ w_o

        inputs = [x, self.kv_a_proj_with_mqa.weight,
                  self.kv_a_layernorm.weight, self.kv_b_proj.weight,
                  self.o_proj.weight]
        if c.q_lora_rank:
            inputs += [self.q_a_proj.weight, self.q_a_layernorm.weight,
                       self.q_b_proj.weight]
        else:
            inputs += [self.q_proj.weight]
        return _apply("mla_attention", impl, inputs)


class DeepSeekV2DecoderLayer(nn.Layer):
    def __init__(self, c: DeepSeekV2Config, layer_idx: int = 0):
        super().__init__()
        self.c = c
        self.input_layernorm = nn.RMSNorm(c.hidden_size, c.rms_norm_eps)
        self.self_attn = MLAttention(c)
        self.post_attention_layernorm = nn.RMSNorm(c.hidden_size,
                                                   c.rms_norm_eps)
        if layer_idx < c.first_k_dense_replace:
            self.mlp = LlamaMLP(c)
        else:
            self.mlp = MoELayer(
                c.hidden_size, c.moe_intermediate_size, c.num_experts,
                top_k=c.top_k, capacity_factor=c.capacity_factor,
                activation="swiglu", dropless=c.moe_dropless,
                shared_expert_hidden=c.shared_expert_intermediate_size,
                z_loss_weight=c.router_z_loss_weight)

    def forward(self, x, cos, sin, attn_mask=None):
        h = x + self.self_attn(self.input_layernorm(x), cos, sin, attn_mask)
        return h + self.mlp(self.post_attention_layernorm(h))


class DeepSeekV2Model(nn.Layer):
    def __init__(self, config: DeepSeekV2Config):
        super().__init__()
        self.config = config
        init = I.Normal(0.0, config.initializer_range)
        self.embed_tokens = nn.Embedding(config.vocab_size,
                                         config.hidden_size)
        self.embed_tokens.weight._data = init(
            [config.vocab_size, config.hidden_size], "float32")
        self.embed_tokens.weight._sharding_spec = P(MP_AXIS, None)
        self.layers = nn.LayerList(
            [DeepSeekV2DecoderLayer(config, i)
             for i in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)
        cos, sin = precompute_rope(config.qk_rope_head_dim,
                                   config.max_position_embeddings,
                                   config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

    def aux_loss(self):
        total = None
        for layer in self.layers:
            la = getattr(layer.mlp, "l_aux", None)
            if la is not None:
                total = la if total is None else total + la
        return total

    def forward(self, input_ids, attn_mask=None):
        x = self.embed_tokens(input_ids)
        cos, sin = self.rope_cos._data, self.rope_sin._data
        for layer in self.layers:
            if self.config.recompute and self.training:
                from ..distributed.recompute import recompute
                x = recompute(layer, x, cos, sin, attn_mask)
            else:
                x = layer(x, cos, sin, attn_mask)
        return self.norm(x)


class DeepSeekV2ForCausalLM(nn.Layer):
    def __init__(self, config: DeepSeekV2Config):
        super().__init__()
        self.config = config
        self.model = DeepSeekV2Model(config)
        self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                 bias_attr=False)
        self.lm_head.weight._sharding_spec = P(None, MP_AXIS)

    def forward(self, input_ids, labels=None, attn_mask=None):
        h = self.model(input_ids, attn_mask)
        logits = self.lm_head(h)
        if labels is not None:
            tok_loss = ParallelCrossEntropy()(logits, labels)
            loss = tok_loss.mean()
            aux = self.model.aux_loss()
            if aux is not None and self.training:
                loss = loss + self.config.aux_loss_weight * aux
            return loss, logits
        return logits
