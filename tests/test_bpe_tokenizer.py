"""Byte-level BPE tokenizer tests (ref capability: PaddleNLP GPT/Llama
tokenizers — paddlenlp/transformers/gpt/tokenizer.py)."""

import numpy as np

from paddle_tpu.text import BPETokenizer, train_bpe

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "the quick brown fox is quick and brown",
    "lazy dogs sleep all day the lazy way",
    "pack my box with five dozen liquor jugs",
] * 4


def _tok(vocab_size=400):
    vocab, merges = train_bpe(CORPUS, vocab_size)
    return BPETokenizer(vocab, merges)


def test_roundtrip_exact():
    tok = _tok()
    for text in ["the quick brown fox", "lazy dog day",
                 "unseen wordforms too", "punctuation, and; symbols!"]:
        ids = tok.encode(text)
        assert all(isinstance(i, int) for i in ids)
        assert tok.decode(ids) == text


def test_merges_compress_frequent_words():
    tok = _tok()
    # 'the' is the most frequent word: after training it should be few
    # tokens, while a random unseen string stays byte-level
    assert len(tok.encode("the")) <= 2
    assert len(tok.encode("zxqj")) >= 3


def test_unicode_bytes_roundtrip():
    tok = _tok()
    text = "héllo wörld — ¥1000"
    assert tok.decode(tok.encode(text)) == text


def test_batched_call_padding():
    tok = _tok()
    out = tok(["the quick fox", "dog"], max_length=16)
    assert out["input_ids"].shape == (2, 16)
    assert out["attention_mask"].shape == (2, 16)
    n1 = int(out["attention_mask"][1].sum())
    assert n1 < 16  # short text padded
    np.testing.assert_array_equal(out["input_ids"][1, n1:],
                                  tok.vocab[tok.pad_token])


def test_train_respects_vocab_size_and_specials():
    vocab, merges = train_bpe(CORPUS, 300, special_tokens=("<eos>", "<pad>"))
    assert vocab["<eos>"] == 0 and vocab["<pad>"] == 1
    assert len(vocab) <= 300
    assert len(merges) > 0


class TestReviewRegressions:
    def test_space_attaches_to_following_word(self):
        """GPT-2 pre-tokenizer parity: ' world' is ONE piece, so merges can
        produce the space-prefixed word tokens pretrained vocabs contain."""
        tok = _tok()
        pieces = tok._pat.findall("hello world")
        assert pieces == ["hello", " world"]
        # and the trained tokenizer merges ' the' into few tokens
        assert len(tok.encode(" the")) <= 2

    def test_no_truncation_keeps_full_length(self):
        tok = _tok()
        long = " ".join(["unseenworder"] * 40)
        n = len(tok.encode(long))
        assert n > 16
        out = tok([long, "dog"], max_length=16, padding=True,
                  truncation=False)
        assert out["input_ids"].shape[1] == n  # nothing chopped
        assert int(out["attention_mask"][0].sum()) == n

    def test_train_bpe_survives_merge_collisions(self):
        # tiny corpus engineered so multiple merge paths reach the same
        # string; training must keep going instead of stopping early
        corpus = ["aaab aab ab aaab aab ab abc bc"] * 8
        vocab, merges = train_bpe(corpus, 290)
        assert len(merges) >= 3


def test_temperature_zero_is_greedy():
    import paddle_tpu as paddle
    from paddle_tpu.generation import generate
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny_config
    paddle.seed(0)
    c = gpt_tiny_config(num_hidden_layers=1)
    model = GPTForCausalLM(c)
    model.eval()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, c.vocab_size, (1, 4)).astype(np.int32))
    greedy, _ = generate(model, ids, max_new_tokens=3,
                         decode_strategy="greedy_search")
    paddle.seed(99)
    t0, _ = generate(model, ids, max_new_tokens=3,
                     decode_strategy="sampling", temperature=0.0)
    np.testing.assert_array_equal(greedy.numpy(), t0.numpy())


class TestNativeBPE:
    def test_native_matches_python_loop(self):
        from paddle_tpu.native import available
        if not available():
            import pytest
            pytest.skip("native runtime unavailable")
        tok = _tok()
        assert tok._native is not None  # C++ path active
        texts = ["the quick brown fox", " the lazy dog",
                 "héllo wörld — ¥1000", "punctuation, and; symbols!"]
        for t in texts:
            native_ids = tok.encode(t)
            # python reference loop
            unk = tok.vocab.get(tok.unk_token, 0)
            py_ids = [tok.vocab.get(s, unk) for s in tok.tokenize(t)]
            assert native_ids == py_ids, t
            assert tok.decode(native_ids) == t

    def test_native_throughput_not_worse(self):
        import time
        from paddle_tpu.native import available
        if not available():
            import pytest
            pytest.skip("native runtime unavailable")
        tok = _tok()
        text = " ".join(CORPUS) * 20
        t0 = time.perf_counter()
        n1 = len(tok.encode(text))
        t_native = time.perf_counter() - t0
        tok._native = None  # force the python loop (cold cache)
        tok._cache.clear()
        t0 = time.perf_counter()
        n2 = len(tok.encode(text))
        t_py = time.perf_counter() - t0
        assert n1 == n2
        # smoke bound only: native shouldn't be dramatically slower
        assert t_native < t_py * 5 + 0.5, (t_native, t_py)

    def test_native_long_piece_not_truncated(self):
        from paddle_tpu.native import available
        if not available():
            import pytest
            pytest.skip("native runtime unavailable")
        tok = _tok()
        long_run = "z" * 6000  # single pre-token piece > 4096 symbols
        ids = tok.encode(long_run)
        tok2 = _tok()
        tok2._native = None
        py_ids = tok2.encode(long_run)
        assert ids == py_ids
        assert tok.decode(ids) == long_run

    def test_native_thread_safety(self):
        import threading
        from paddle_tpu.native import available
        if not available():
            import pytest
            pytest.skip("native runtime unavailable")
        tok = _tok()
        texts = ["the quick brown fox", "lazy dogs sleep", "pack my box",
                 "five dozen jugs"] * 8
        expect = {t: tok.encode(t) for t in set(texts)}
        errors = []

        def worker(seq):
            for t in seq:
                if tok.encode(t) != expect[t]:
                    errors.append(t)

        threads = [threading.Thread(target=worker, args=(texts,))
                   for _ in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errors

    def test_pickle_and_deepcopy_rebuild_native(self):
        import copy
        import pickle
        tok = _tok()
        ref = tok.encode("the quick brown fox")
        c = copy.deepcopy(tok)
        assert c.encode("the quick brown fox") == ref
        p = pickle.loads(pickle.dumps(tok))
        assert p.encode("the quick brown fox") == ref
        # and the ORIGINAL still works after the copies are dropped
        del c, p
        import gc
        gc.collect()
        assert tok.encode("the quick brown fox") == ref
