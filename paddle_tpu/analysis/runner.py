"""Discovery + orchestration: build one :class:`PackageIndex` over the
requested files, run every rule pass, drop suppressed / out-of-severity
findings, return the rest sorted by location."""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

from . import (rules_collective, rules_effects, rules_hostsync,
               rules_kernel, rules_memory, rules_rng, rules_sharding,
               rules_threads, rules_trace)
from .callgraph import PackageIndex
from .model import Config, Finding, is_suppressed

_PASSES = (rules_trace, rules_hostsync, rules_rng, rules_threads,
           rules_kernel, rules_collective, rules_sharding, rules_memory,
           rules_effects)


def discover(root: str) -> List[Tuple[str, str, str]]:
    """-> [(modname, abs_path, rel_path)] for every .py under ``root``.
    Module names are dotted paths rooted at the basename of ``root`` so
    intra-package imports (absolute and relative) resolve."""
    root = os.path.abspath(root)
    base = os.path.basename(root)
    out = []
    if os.path.isfile(root):
        rel = os.path.basename(root)
        return [(os.path.splitext(rel)[0], root, rel)]
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if not d.startswith((".", "__pycache__")))
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, os.path.dirname(root))
            parts = os.path.relpath(path, root).replace(os.sep, "/")
            mod = parts[:-3].replace("/", ".")
            if mod.endswith(".__init__"):
                mod = mod[: -len(".__init__")]
            modname = base if mod == "__init__" else f"{base}.{mod}"
            out.append((modname, path, rel.replace(os.sep, "/")))
    return out


def expand_changed_with_factories(
        files: List[Tuple[str, str, str]],
        changed_abs: set,
        index: Optional[PackageIndex] = None
) -> List[Tuple[str, str, str]]:
    """Grow a ``--changed-only`` file selection with kernel *call-site*
    files whose factory module changed.

    A pallas kernel is often built in one module (the factory) and
    launched from another; editing only the factory leaves the call-site
    file out of the changed set, so the kernel-structure passes — which
    anchor findings at the ``pallas_call`` site — silently skip the
    launch that the edit just broke.  Index the full selection once,
    and for every kernel call whose *kernel function* is defined in a
    changed module, pull the call-site file back in."""
    picked = [t for t in files if os.path.abspath(t[1]) in changed_abs]
    if not picked or len(picked) == len(files):
        return picked
    from . import kernelmodel as km
    if index is None:
        index = PackageIndex.from_files(files)
    have = {os.path.abspath(t[1]) for t in picked}
    extras = []
    for site in km.collect_kernel_calls(index):
        if site.kernel_fi is None:
            continue
        factory_mi = index.modules.get(site.kernel_fi.modname)
        if factory_mi is None:
            continue
        if os.path.abspath(factory_mi.path) not in changed_abs:
            continue
        site_abs = os.path.abspath(site.mi.path)
        if site_abs in have:
            continue
        have.add(site_abs)
        extras.extend(t for t in files
                      if os.path.abspath(t[1]) == site_abs)
    return picked + extras


def expand_changed_with_fusion(
        files: List[Tuple[str, str, str]],
        changed_abs: set) -> List[Tuple[str, str, str]]:
    """Factory expansion plus fusion-candidate dirtiness: when a changed
    file hosts one member of a PF404 fusion candidate (or a registered
    PE505 composition), pull in the files hosting the *other* members.

    PE505's legality verdict is a property of the pair — retiling the
    producer's out_specs can invert the seam ordering without touching
    the consumer's file, so a selection that only re-analyzes the edited
    side would re-certify a fusion it can no longer see both halves
    of."""
    picked = [t for t in files if os.path.abspath(t[1]) in changed_abs]
    if not picked or len(picked) == len(files):
        return picked
    index = PackageIndex.from_files(files)
    picked = expand_changed_with_factories(files, changed_abs, index)
    from . import effectsmodel as em
    from . import vmemmodel as vm
    sites = vm.canonical_sites(index)
    groups = [[c["producer"], c["consumer"]]
              for c in vm.fusion_candidates(index)]
    groups += [list(comp["members"]) for comp in em.COMPOSITIONS]
    have = {os.path.abspath(t[1]) for t in picked}
    extras = []
    for group in groups:
        member_paths = set()
        for kernel in group:
            qn = vm._CHAIN_SITE.get(kernel)
            site = sites.get(qn) if qn else None
            if site is not None:
                member_paths.add(os.path.abspath(site.mi.path))
        if not member_paths & changed_abs:
            continue
        for pth in sorted(member_paths - have):
            have.add(pth)
            extras.extend(t for t in files
                          if os.path.abspath(t[1]) == pth)
    return picked + extras


def _filter(findings: List[Finding], index: PackageIndex,
            cfg: Config) -> List[Finding]:
    by_rel = {mi.rel: mi for mi in index.modules.values()}
    out = []
    for f in findings:
        if f.severity == "info" and not cfg.strict:
            continue
        mi = by_rel.get(f.path)
        if mi is not None and is_suppressed(f, mi.suppress_lines,
                                            mi.suppress_file):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def analyze_files(files: List[Tuple[str, str, str]],
                  cfg: Optional[Config] = None) -> List[Finding]:
    """Analyze an explicit ``[(modname, abs_path, rel_path)]`` set — the
    ``--changed-only`` entry point, where the caller has already filtered
    ``discover()`` output but needs rel paths (and so baseline keys) to
    stay repo-relative."""
    cfg = cfg or Config()
    index = PackageIndex.from_files(files)
    findings: List[Finding] = []
    for p in _PASSES:
        findings.extend(p.run(index, cfg))
    return _filter(findings, index, cfg)


def analyze_paths(paths: List[str],
                  cfg: Optional[Config] = None) -> List[Finding]:
    files: List[Tuple[str, str, str]] = []
    for p in paths:
        files.extend(discover(p))
    return analyze_files(files, cfg)


def analyze_source(source: str, cfg: Optional[Config] = None,
                   modname: str = "snippet",
                   rel: str = "snippet.py") -> List[Finding]:
    """Single-snippet entry point for the fixture tests."""
    cfg = cfg or Config()
    index = PackageIndex.from_source(source, modname=modname, rel=rel)
    findings: List[Finding] = []
    for p in _PASSES:
        findings.extend(p.run(index, cfg))
    return _filter(findings, index, cfg)
