"""DeepSeek-V2 MLA tests (ref capability: PaddleNLP
paddlenlp/transformers/deepseek_v2/modeling.py — SURVEY §2.4 DeepSeekMoE
row). Checks the latent-attention mechanism: shapes, causality, the
decoupled-rope shared key head, and end-to-end training."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.deepseek import (DeepSeekV2ForCausalLM, MLAttention,
                                        deepseek_v2_tiny_config)


def _ids(B, S, V, seed=0):
    rng = np.random.RandomState(seed)
    return paddle.to_tensor(rng.randint(0, V, (B, S)).astype(np.int32))


def test_mla_forward_shapes():
    paddle.seed(0)
    c = deepseek_v2_tiny_config()
    model = DeepSeekV2ForCausalLM(c)
    model.eval()
    ids = _ids(2, 16, c.vocab_size)
    logits = model(ids)
    assert logits.shape == [2, 16, c.vocab_size]
    loss, _ = model(ids, labels=ids)
    assert np.isfinite(float(loss.numpy()))


def test_mla_low_rank_param_shapes():
    """The point of MLA: KV path goes through the kv_lora_rank latent."""
    c = deepseek_v2_tiny_config()
    attn = MLAttention(c)
    nh = c.num_attention_heads
    assert attn.kv_a_proj_with_mqa.weight.shape == \
        [c.hidden_size, c.kv_lora_rank + c.qk_rope_head_dim]
    assert attn.kv_b_proj.weight.shape == \
        [c.kv_lora_rank, nh * (c.qk_nope_head_dim + c.v_head_dim)]
    assert attn.q_b_proj.weight.shape == \
        [c.q_lora_rank, nh * (c.qk_nope_head_dim + c.qk_rope_head_dim)]
    assert attn.o_proj.weight.shape == [nh * c.v_head_dim, c.hidden_size]


def test_mla_causality():
    paddle.seed(0)
    c = deepseek_v2_tiny_config(first_k_dense_replace=2)  # dense FFN only
    model = DeepSeekV2ForCausalLM(c)
    model.eval()
    ids = _ids(1, 12, c.vocab_size, seed=1)
    base = model(ids).numpy()
    mut = ids.numpy().copy()
    mut[0, -1] = (mut[0, -1] + 1) % c.vocab_size
    out = model(paddle.to_tensor(mut)).numpy()
    np.testing.assert_allclose(base[0, :-1], out[0, :-1],
                               rtol=1e-4, atol=1e-5)


def test_mla_no_q_lora_variant():
    paddle.seed(0)
    c = deepseek_v2_tiny_config(q_lora_rank=None)
    model = DeepSeekV2ForCausalLM(c)
    model.eval()
    out = model(_ids(1, 8, c.vocab_size))
    assert out.shape == [1, 8, c.vocab_size]


def test_mla_training_step_decreases_loss():
    paddle.seed(0)
    c = deepseek_v2_tiny_config(num_hidden_layers=1,
                                first_k_dense_replace=0)
    model = DeepSeekV2ForCausalLM(c)
    model.train()
    from paddle_tpu.optimizer import AdamW
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
    ids = _ids(4, 16, c.vocab_size, seed=2)
    losses = []
    for _ in range(6):
        loss, _ = model(ids, labels=ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] - 0.1, losses


def test_mla_all_projections_receive_grads():
    """Review regression: attention math must run inside the dispatch apply
    so q/kv/o projection weights all train."""
    paddle.seed(0)
    c = deepseek_v2_tiny_config(num_hidden_layers=1,
                                first_k_dense_replace=1)
    model = DeepSeekV2ForCausalLM(c)
    model.train()
    ids = _ids(2, 16, c.vocab_size, seed=4)
    loss, _ = model(ids, labels=ids)
    loss.backward()
    attn = model.model.layers[0].self_attn
    for name in ("q_a_proj", "q_b_proj", "kv_a_proj_with_mqa", "kv_b_proj",
                 "o_proj"):
        g = getattr(attn, name).weight.grad
        assert g is not None, name
        assert float(np.abs(g.numpy()).max()) > 0, name


def test_mla_mask_composes_with_causal():
    paddle.seed(0)
    c = deepseek_v2_tiny_config(first_k_dense_replace=2)
    model = DeepSeekV2ForCausalLM(c)
    model.eval()
    ids = _ids(1, 8, c.vocab_size, seed=5)
    full = np.ones((1, 1, 8, 8), bool)
    base = model(ids).numpy()
    masked = model(ids, attn_mask=paddle.to_tensor(full)).numpy()
    np.testing.assert_allclose(base, masked, rtol=1e-4, atol=1e-5)
    # a mask hiding the first key position changes the output
    part = full.copy()
    part[..., 0] = False
    out = model(ids, attn_mask=paddle.to_tensor(part)).numpy()
    assert np.abs(out - base).max() > 1e-5
