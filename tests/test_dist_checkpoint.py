"""Distributed checkpoint: shard save + cross-topology reload (SURVEY §5.4)."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.mesh import build_hybrid_mesh
from paddle_tpu.distributed import checkpoint as ckpt


def _sharded(arr, mesh, spec):
    return jax.device_put(arr, NamedSharding(mesh, spec))


def test_roundtrip_replicated(tmp_path):
    w = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    sd = {"w": Tensor(jnp.asarray(w))}
    ckpt.save_state_dict(sd, str(tmp_path))
    tgt = {"w": Tensor(jnp.zeros((8, 4), jnp.float32))}
    ckpt.load_state_dict(tgt, str(tmp_path))
    np.testing.assert_allclose(np.asarray(tgt["w"]._data), w)


def test_cross_topology_reload(tmp_path):
    """Save sharded (dp=2, mp=4) on dim0/dim1; load into (dp=8) dim0-only."""
    rng = np.random.RandomState(1)
    w = rng.randn(16, 8).astype(np.float32)
    b = rng.randn(16).astype(np.float32)

    mesh_a = build_hybrid_mesh(dp_degree=2, mp_degree=4)
    sd = {"w": Tensor(_sharded(jnp.asarray(w), mesh_a, P("dp", "mp"))),
          "b": Tensor(_sharded(jnp.asarray(b), mesh_a, P("mp")))}
    ckpt.save_state_dict(sd, str(tmp_path))

    mesh_b = build_hybrid_mesh(dp_degree=8)
    tgt = {"w": Tensor(_sharded(jnp.zeros((16, 8), jnp.float32), mesh_b,
                                P("dp", None))),
           "b": Tensor(_sharded(jnp.zeros((16,), jnp.float32), mesh_b,
                                P(None)))}
    ckpt.load_state_dict(tgt, str(tmp_path))
    np.testing.assert_allclose(np.asarray(tgt["w"]._data), w)
    np.testing.assert_allclose(np.asarray(tgt["b"]._data), b)
    # target sharding preserved
    assert tgt["w"]._data.sharding.spec == P("dp", None)


def test_async_save(tmp_path):
    w = np.random.RandomState(2).randn(4, 4).astype(np.float32)
    sd = {"w": Tensor(jnp.asarray(w))}
    ckpt.save_state_dict(sd, str(tmp_path), async_save=True)
    ckpt.wait_async_saves()
    tgt = {"w": Tensor(jnp.zeros((4, 4), jnp.float32))}
    ckpt.load_state_dict(tgt, str(tmp_path))
    np.testing.assert_allclose(np.asarray(tgt["w"]._data), w)


def test_raw_arrays_and_bf16(tmp_path):
    w = jnp.asarray(np.random.RandomState(3).randn(4, 4), jnp.bfloat16)
    sd = {"w": w}
    ckpt.save_state_dict(sd, str(tmp_path))
    tgt = {"w": jnp.zeros((4, 4), jnp.bfloat16)}
    ckpt.load_state_dict(tgt, str(tmp_path))
    np.testing.assert_allclose(np.asarray(tgt["w"], np.float32),
                               np.asarray(w, np.float32))


def test_shape_mismatch_raises(tmp_path):
    sd = {"w": Tensor(jnp.zeros((4, 4), jnp.float32))}
    ckpt.save_state_dict(sd, str(tmp_path))
    tgt = {"w": Tensor(jnp.zeros((2, 4), jnp.float32))}
    import pytest
    with pytest.raises(ValueError):
        ckpt.load_state_dict(tgt, str(tmp_path))
