"""Pipeline-schedule generator: FThenB / 1F1B / interleaved VPP / ZBH1.

Reference parity: python/paddle/distributed/passes/pipeline_scheduler_pass.py
(schedules FThenB, 1F1B, Eager1F1B, VPP, ZBH1 — SURVEY §2.3 P6) and
fleet/meta_parallel/pipeline_parallel.py's runtime orderings.

TPU-native role: the compiled SPMD pipeline (`distributed/pipeline.py`)
expresses the schedule as a scan over ticks, and XLA's latency-hiding
scheduler owns actual compute/comm overlap. This module is the *explicit*
schedule layer the reference exposes: it generates per-stage timetables
(which op — forward F, backward-dgrad B, backward-wgrad W — of which
microbatch/chunk runs at which tick), validates dependencies, and accounts
bubbles and peak in-flight activations. Uses: host-driven interleaved
execution across DCN slices, schedule visualization/debugging, and the
auto-tuner's analytic cost model (bubble ratio per candidate pp degree).

Model: every op costs one tick; stage-to-stage transfer is free (latency is
folded into the dependency "completes before consumer's tick"). ZBH1 splits
the backward into B (activation/dgrad, unlocks the upstream stage) and W
(weight grad, pure filler work) — scheduling W into warm-up/drain holes is
exactly the zero-bubble-H1 trick.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

__all__ = [
    "Op", "Schedule", "generate_schedule", "SCHEDULERS",
    "fthenb_schedule", "one_f_one_b_schedule", "interleaved_1f1b_schedule",
    "zbh1_schedule",
]


@dataclass(frozen=True)
class Op:
    """One unit of pipeline work.

    phase: 'F' forward, 'B' backward-dgrad, 'W' backward-wgrad.
    chunk: virtual-stage index (0 unless VPP); the model chunk this op runs
    on. Global layer block = chunk * n_stages + stage (Megatron ordering).
    """
    stage: int
    mb: int
    phase: str
    chunk: int = 0


class Schedule:
    """Per-stage timetables: timeline[s][t] is an Op or None (bubble)."""

    def __init__(self, n_stages: int, n_microbatches: int, n_chunks: int,
                 timeline: List[List[Optional[Op]]], split_w: bool):
        self.n_stages = n_stages
        self.n_microbatches = n_microbatches
        self.n_chunks = n_chunks
        self.timeline = timeline
        self.split_w = split_w

    @property
    def n_ticks(self) -> int:
        return max(len(row) for row in self.timeline)

    def bubble_ratio(self) -> float:
        """Idle fraction of the stage×tick grid (the pipeline bubble)."""
        total = self.n_stages * self.n_ticks
        busy = sum(1 for row in self.timeline for op in row if op is not None)
        return 1.0 - busy / total

    def peak_inflight(self, stage: int) -> int:
        """Max microbatch-activations held at `stage` (F done, B not yet) —
        the memory figure 1F1B bounds at ~n_stages vs GPipe's M."""
        live = 0
        peak = 0
        for op in self.timeline[stage]:
            if op is None:
                continue
            if op.phase == "F":
                live += 1
                peak = max(peak, live)
            elif op.phase == "B":
                live -= 1
        return peak

    def validate(self) -> None:
        """Assert completeness + dependency order (F chain down the virtual
        stages, B chain back up, W after its B, one op per stage-tick)."""
        S, M, C = self.n_stages, self.n_microbatches, self.n_chunks
        done: Dict[Tuple, int] = {}  # (phase, vstage, mb) -> finish tick
        for s, row in enumerate(self.timeline):
            for t, op in enumerate(row):
                if op is None:
                    continue
                if op.stage != s:
                    raise AssertionError(f"op {op} on wrong row {s}")
                key = (op.phase, op.chunk * S + s, op.mb)
                if key in done:
                    raise AssertionError(f"duplicate {key}")
                done[key] = t + 1
        phases = ["F", "B", "W"] if self.split_w else ["F", "B"]
        V = S * C
        for mb in range(M):
            for v in range(V):
                for ph in phases:
                    if (ph, v, mb) not in done:
                        raise AssertionError(f"missing {(ph, v, mb)}")
        for (ph, v, mb), t_end in done.items():
            t_start = t_end - 1
            if ph == "F" and v > 0:
                if done[("F", v - 1, mb)] > t_start:
                    raise AssertionError(f"F dep violated at v={v} mb={mb}")
            if ph == "B":
                prev = done[("B", v + 1, mb)] if v < V - 1 \
                    else done[("F", V - 1, mb)]
                if prev > t_start:
                    raise AssertionError(f"B dep violated at v={v} mb={mb}")
            if ph == "W" and done[("B", v, mb)] > t_start:
                raise AssertionError(f"W dep violated at v={v} mb={mb}")


def _simulate(n_stages: int, n_microbatches: int, n_chunks: int,
              policy, split_w: bool) -> Schedule:
    """Greedy tick simulator. Each tick, every stage runs the ready op its
    `policy(stage, ready_ops, issued_counts)` picks (or bubbles).

    Readiness is evaluated against ops finished on PREVIOUS ticks, so a
    consumer never runs in the same tick its producer finishes — the 1-tick
    p2p latency of the reference's send/recv handshake.
    """
    S, M, C = n_stages, n_microbatches, n_chunks
    V = S * C
    done: Dict[Tuple, int] = {}
    todo = {("F", c * S + s, m) for s in range(S) for c in range(C)
            for m in range(M)}
    todo |= {("B", c * S + s, m) for s in range(S) for c in range(C)
             for m in range(M)}
    if split_w:
        todo |= {("W", c * S + s, m) for s in range(S) for c in range(C)
                 for m in range(M)}
    timeline: List[List[Optional[Op]]] = [[] for _ in range(S)]
    issued = [dict(F=0, B=0, W=0) for _ in range(S)]
    t = 0
    limit = 16 * (len(todo) + S)  # safety net; real schedules end well under
    while todo and t < limit:
        picks = []
        for s in range(S):
            ready = []
            for (ph, v, m) in todo:
                if v % S != s:
                    continue
                if ph == "F":
                    ok = v == 0 or done.get(("F", v - 1, m), 10**9) <= t
                elif ph == "B":
                    prev = ("B", v + 1, m) if v < V - 1 else ("F", V - 1, m)
                    ok = done.get(prev, 10**9) <= t
                else:
                    ok = done.get(("B", v, m), 10**9) <= t
                if ok:
                    ready.append(Op(s, m, ph, v // S))
            picks.append(policy(s, ready, issued[s]))
        for s, op in enumerate(picks):
            timeline[s].append(op)
            if op is not None:
                todo.discard((op.phase, op.chunk * S + s, op.mb))
                done[(op.phase, op.chunk * S + s, op.mb)] = t + 1
                issued[s][op.phase] += 1
        t += 1
    if todo:
        raise RuntimeError(f"schedule did not converge: {len(todo)} ops left")
    while any(timeline[s] and timeline[s][-1] is None for s in range(S)):
        if all(not timeline[s] or timeline[s][-1] is None for s in range(S)):
            for s in range(S):
                if timeline[s]:
                    timeline[s].pop()
        else:
            break
    n = max(len(row) for row in timeline)
    for row in timeline:
        row.extend([None] * (n - len(row)))
    return Schedule(S, M, C, timeline, split_w)


def _pick(ready: List[Op], phase: str, chunk_order=None) -> Optional[Op]:
    cand = [op for op in ready if op.phase == phase]
    if not cand:
        return None
    if chunk_order == "reversed":
        return min(cand, key=lambda o: (-o.chunk, o.mb))
    return min(cand, key=lambda o: (o.chunk, o.mb))


def fthenb_schedule(n_stages: int, n_microbatches: int) -> Schedule:
    """GPipe order: all forwards, then all backwards. Peak in-flight = M."""
    def policy(s, ready, issued):
        return _pick(ready, "F") or _pick(ready, "B")
    return _simulate(n_stages, n_microbatches, 1, policy, split_w=False)


def one_f_one_b_schedule(n_stages: int, n_microbatches: int) -> Schedule:
    """1F1B: warm up S-s forwards, then alternate; peak in-flight ≤ S-s.

    Same bubble as FThenB (2(S-1) tick overhead) but activation memory is
    bounded by the stage depth instead of the microbatch count — the reason
    the reference defaults to it for pretrain.
    """
    S = n_stages

    def policy(s, ready, issued):
        in_flight = issued["F"] - issued["B"]
        if in_flight >= S - s:  # steady state: drain one before next F
            return _pick(ready, "B")  # cap in-flight: bubble rather than F
        return _pick(ready, "F") or _pick(ready, "B")
    return _simulate(S, n_microbatches, 1, policy, split_w=False)


def interleaved_1f1b_schedule(n_stages: int, n_microbatches: int,
                              n_chunks: int) -> Schedule:
    """VPP: each stage owns `n_chunks` virtual stages (chunk c, stage s →
    virtual stage c·S+s). Chunk-cyclic forwards shrink the warm-up bubble by
    ~1/n_chunks at the cost of more in-flight microbatches."""
    S = n_stages

    def policy(s, ready, issued):
        in_flight = issued["F"] - issued["B"]
        if in_flight >= max(1, (S - s) + (n_chunks - 1) * S // 2):
            op = _pick(ready, "B", chunk_order="reversed")
            if op is not None:
                return op
        return _pick(ready, "F") or _pick(ready, "B",
                                          chunk_order="reversed")
    return _simulate(S, n_microbatches, n_chunks, policy, split_w=False)


def zbh1_schedule(n_stages: int, n_microbatches: int) -> Schedule:
    """ZBH1 (zero-bubble, memory class H1): backward split into dgrad B
    (critical path) and wgrad W (filler). B/F follow 1F1B; W fills what
    would otherwise be drain bubbles, so idle time drops below 1F1B while
    peak activation memory stays at the 1F1B bound."""
    S = n_stages

    def policy(s, ready, issued):
        in_flight = issued["F"] - issued["B"]
        w_backlog = issued["B"] - issued["W"]
        # H1 memory contract: the deferred-W window (retained input +
        # cotangent pairs) stays O(S); once it fills, drain a W before
        # admitting new forward work
        if w_backlog >= S:
            op = _pick(ready, "W")
            if op is not None:
                return op
        if in_flight >= S - s:
            # at the 1F1B memory cap: drain a dgrad, else fill the would-be
            # bubble with a deferred weight-grad (the ZB trick) — never F
            return _pick(ready, "B") or _pick(ready, "W")
        return _pick(ready, "F") or _pick(ready, "B") or _pick(ready, "W")
    return _simulate(S, n_microbatches, 1, policy, split_w=True)


SCHEDULERS = {
    "FThenB": fthenb_schedule,
    "1F1B": one_f_one_b_schedule,
    "VPP": interleaved_1f1b_schedule,
    "ZBH1": zbh1_schedule,
}


def generate_schedule(mode: str, n_stages: int, n_microbatches: int,
                      n_chunks: int = 1) -> Schedule:
    """`pipeline_scheduler_pass`-parity entry: mode ∈ SCHEDULERS."""
    if mode not in SCHEDULERS:
        raise ValueError(f"unknown schedule {mode!r}; "
                         f"options: {sorted(SCHEDULERS)}")
    if mode == "VPP":
        return interleaved_1f1b_schedule(n_stages, n_microbatches, n_chunks)
    if n_chunks != 1:
        raise ValueError(f"n_chunks={n_chunks} requires mode='VPP'; "
                         f"{mode} schedules a single chunk per stage")
    return SCHEDULERS[mode](n_stages, n_microbatches)
