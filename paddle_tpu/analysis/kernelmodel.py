"""Static model of every ``pl.pallas_call`` site (docs/ANALYSIS.md,
kernel-verification section).

Pure ``ast`` like the rest of the package: for each call site the model
recovers — through a flow-insensitive local-variable environment — the
grid (and ``PrefetchScalarGridSpec``), every ``BlockSpec`` with its block
shape and index_map (lambda, local/module ``def``, or a
``functools.partial`` over one), the scalar-prefetch count, scratch
shapes/dtypes, ``out_shape`` ShapeDtypeStructs, ``input_output_aliases``
and the resolved kernel body function.  A small abstract interpreter then
walks each index_map over its grid domain: grid ids are bounded by
construction, constants are exact, and scalar-prefetch table reads are
*unbounded* unless syntactically routed through a clamp
(``jnp.clip``/``minimum``/``maximum``/``where``/``%``) — the idiom every
shipped page map uses, and the thing whose absence is the silent-OOB bug
class (rule PK101).

Everything here degrades to "unknown" rather than guessing: a spec list
built by a helper function, a computed alias dict, or a ``*refs`` kernel
simply opts that call site out of the checks that need the missing piece.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import (FunctionInfo, ModuleInfo, PackageIndex, _last_name,
                        partial_inner, walk_shallow)

#: call names that bound their result (syntactic clamp idioms)
CLAMP_FUNCS = {"clip", "minimum", "maximum", "where", "mod", "remainder"}

#: sub-f32 dtype attribute names (PK104)
SUB_F32_DTYPES = {"bfloat16", "float16", "float8_e4m3fn", "float8_e5m2"}


def unparse(node: ast.AST, limit: int = 60) -> str:
    try:
        s = ast.unparse(node)
    except Exception:  # pragma: no cover - exotic node
        s = type(node).__name__
    s = " ".join(s.split())
    return s if len(s) <= limit else s[: limit - 3] + "..."


def _int_const(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _int_const(node.operand)
        return -v if v is not None else None
    return None


def _seq_elts(node: ast.AST) -> Optional[List[ast.AST]]:
    if isinstance(node, (ast.List, ast.Tuple)):
        return list(node.elts)
    return None


# ---------------------------------------------------------------------------
# local-variable environment
# ---------------------------------------------------------------------------

class Env:
    """Flow-insensitive name -> value-AST map for one enclosing scope
    chain (module globals, then each enclosing function outer-to-inner,
    so inner bindings win). Tuple-unpacking targets are recorded as
    *unknown* by omission."""

    def __init__(self, mi: ModuleInfo, fi: Optional[FunctionInfo]):
        self.mi = mi
        self.fi = fi
        self.values: Dict[str, ast.AST] = {}
        for node in mi.tree.body:
            self._record(node)
        if fi is not None:
            parts = fi.qualname.split(".")
            for i in range(1, len(parts) + 1):
                qn = ".".join(parts[:i])
                anc = mi.functions.get(qn)
                if anc is not None and not isinstance(anc.node, ast.Lambda):
                    for node in walk_shallow(anc.node):
                        self._record(node)

    def _record(self, node: ast.AST) -> None:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            self.values[node.targets[0].id] = node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            self.values[node.target.id] = node.value

    def resolve(self, node: Optional[ast.AST],
                _depth: int = 0) -> Optional[ast.AST]:
        """Chase simple ``Name`` indirections (bounded)."""
        while isinstance(node, ast.Name) and _depth < 8:
            nxt = self.values.get(node.id)
            if nxt is None or nxt is node:
                break
            node = nxt
            _depth += 1
        return node


# ---------------------------------------------------------------------------
# index maps / block specs
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class IndexMapModel:
    params: List[str]                       # positional, partial-bound removed
    returns: List[List[ast.AST]]            # one list of components per return
    body: List[ast.stmt]                    # statements to scan for clamps
    node: ast.AST                           # the lambda / def AST
    text: str = ""


@dataclasses.dataclass
class BlockSpecModel:
    node: ast.AST                           # the pl.BlockSpec(...) call
    block_shape: Optional[List[ast.AST]]    # None: absent or non-literal
    index_map: Optional[IndexMapModel]      # None: absent or unresolvable
    memory_space: Optional[str] = None      # "ANY"/"SMEM"/... when given
    resolved: bool = True                   # False: element was not a BlockSpec

    @property
    def rank(self) -> Optional[int]:
        return len(self.block_shape) if self.block_shape is not None else None


@dataclasses.dataclass
class KernelCallSite:
    mi: ModuleInfo
    fi: Optional[FunctionInfo]              # enclosing function (innermost)
    call: ast.Call                          # the pl.pallas_call(...) node
    grid_len: Optional[int] = None
    grid_elts: Optional[List[ast.AST]] = None       # grid component exprs
    n_prefetch: int = 0
    in_specs: Optional[List[BlockSpecModel]] = None
    out_specs: Optional[List[BlockSpecModel]] = None
    out_shapes: Optional[List[ast.AST]] = None      # one expr per output
    scratch: Optional[List[ast.AST]] = None
    aliases: Optional[Dict[int, int]] = None
    has_alias_kw: bool = False
    kernel_fi: Optional[FunctionInfo] = None
    kernel_bound_kw: Set[str] = dataclasses.field(default_factory=set)
    kernel_bound_pos: int = 0               # positional args bound via partial
    arg_exprs: Optional[List[ast.AST]] = None       # the (...)(*args) args

    @property
    def line(self) -> int:
        return self.call.lineno

    @property
    def qualname(self) -> str:
        return self.fi.qualname if self.fi is not None else "<module>"

    @property
    def top_qualname(self) -> str:
        """Outermost enclosing def — the certification unit for PK105."""
        return self.qualname.split(".")[0]

    def kernel_positional_params(self) -> Optional[List[str]]:
        """Kernel-ref parameter names in operand order, or None when the
        kernel is unresolved / uses ``*refs``."""
        if self.kernel_fi is None or isinstance(self.kernel_fi.node,
                                                ast.Lambda):
            return None
        a = self.kernel_fi.node.args
        if a.vararg is not None:
            return None
        params = [p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
        params = params[self.kernel_bound_pos:]
        return [p for p in params if p not in self.kernel_bound_kw]


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------

def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _lookup_def(mi: ModuleInfo, fi: Optional[FunctionInfo],
                name: str) -> Optional[FunctionInfo]:
    if fi is not None:
        parts = fi.qualname.split(".")
        for i in range(len(parts), -1, -1):
            qn = ".".join(parts[:i] + [name]) if i else name
            if qn in mi.functions:
                return mi.functions[qn]
    return mi.functions.get(name)


def _fn_positional(node: ast.AST) -> List[str]:
    a = node.args
    return [p.arg for p in a.posonlyargs] + [p.arg for p in a.args]


def build_index_map(expr: Optional[ast.AST], mi: ModuleInfo,
                    fi: Optional[FunctionInfo],
                    env: Env) -> Optional[IndexMapModel]:
    expr = env.resolve(expr)
    if expr is None:
        return None
    bound_kw: Set[str] = set()
    bound_pos = 0
    inner = partial_inner(expr)
    while inner is not None:
        bound_kw |= {kw.arg for kw in expr.keywords if kw.arg}
        bound_pos += len(expr.args) - 1
        expr = env.resolve(inner)
        inner = partial_inner(expr) if expr is not None else None
    if isinstance(expr, ast.Lambda):
        params = _fn_positional(expr)
        body = expr.body
        comps = list(body.elts) if isinstance(body, ast.Tuple) else [body]
        return IndexMapModel(params=params, returns=[comps],
                             body=[ast.Expr(body)], node=expr,
                             text=unparse(expr))
    if isinstance(expr, ast.Name):
        target = _lookup_def(mi, fi, expr.id)
        if target is None or isinstance(target.node, ast.Lambda):
            return None
        expr = target.node
    if isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef)):
        params = [p for p in _fn_positional(expr)[bound_pos:]
                  if p not in bound_kw]
        rets: List[List[ast.AST]] = []
        for node in walk_shallow(expr):
            if isinstance(node, ast.Return) and node.value is not None:
                v = node.value
                rets.append(list(v.elts) if isinstance(v, ast.Tuple)
                            else [v])
        return IndexMapModel(params=params, returns=rets,
                             body=list(expr.body), node=expr,
                             text=unparse(expr.name
                                          if hasattr(expr, "name") else expr))
    return None


def build_block_spec(expr: Optional[ast.AST], mi: ModuleInfo,
                     fi: Optional[FunctionInfo],
                     env: Env) -> Optional[BlockSpecModel]:
    expr = env.resolve(expr)
    if not isinstance(expr, ast.Call) or _last_name(expr.func) != "BlockSpec":
        return (BlockSpecModel(node=expr, block_shape=None, index_map=None,
                               resolved=False)
                if isinstance(expr, ast.AST) else None)
    shape_expr = expr.args[0] if expr.args else _kw(expr, "block_shape")
    map_expr = (expr.args[1] if len(expr.args) > 1
                else _kw(expr, "index_map"))
    mspace = _kw(expr, "memory_space")
    shape = _seq_elts(env.resolve(shape_expr)) if shape_expr is not None \
        else None
    imap = build_index_map(map_expr, mi, fi, env) if map_expr is not None \
        else None
    return BlockSpecModel(node=expr, block_shape=shape, index_map=imap,
                          memory_space=_last_name(mspace)
                          if mspace is not None else None)


def _spec_list(expr: Optional[ast.AST], mi: ModuleInfo,
               fi: Optional[FunctionInfo],
               env: Env) -> Optional[List[BlockSpecModel]]:
    expr = env.resolve(expr)
    if expr is None:
        return None
    elts = _seq_elts(expr)
    if elts is None:
        # a single BlockSpec is a 1-output/1-input spec
        one = build_block_spec(expr, mi, fi, env)
        return [one] if one is not None and one.resolved else None
    out = []
    for e in elts:
        spec = build_block_spec(e, mi, fi, env)
        if spec is None:
            return None
        out.append(spec)
    return out


def _alias_dict(expr: Optional[ast.AST]) -> Optional[Dict[int, int]]:
    if not isinstance(expr, ast.Dict):
        return None
    out: Dict[int, int] = {}
    for k, v in zip(expr.keys, expr.values):
        ki, vi = (_int_const(k) if k is not None else None), _int_const(v)
        if ki is None or vi is None:
            return None
        out[ki] = vi
    return out


def _resolve_kernel(site: KernelCallSite, index: PackageIndex,
                    env: Env) -> None:
    expr = env.resolve(site.call.args[0]) if site.call.args else None
    if expr is None:
        return
    inner = partial_inner(expr)
    while inner is not None:
        site.kernel_bound_kw |= {kw.arg for kw in expr.keywords if kw.arg}
        site.kernel_bound_pos += len(expr.args) - 1
        expr = env.resolve(inner)
        inner = partial_inner(expr) if expr is not None else None
    if isinstance(expr, ast.Name):
        target = _lookup_def(site.mi, site.fi, expr.id)
        if target is not None:
            site.kernel_fi = target
    if site.kernel_fi is None and site.call.args:
        # factory-built kernels (`kern = make_kernel(...)`): the call
        # graph already resolves factory products and partial locals
        keys = index._funcs_from_arg(site.mi, site.fi, site.call.args[0])
        if len(keys) == 1:
            fi = index.functions.get(next(iter(keys)))
            if fi is not None and not isinstance(fi.node, ast.Lambda):
                site.kernel_fi = fi


def _parse_site(mi: ModuleInfo, fi: Optional[FunctionInfo], call: ast.Call,
                outer: Optional[ast.Call],
                index: PackageIndex) -> KernelCallSite:
    env = Env(mi, fi)
    site = KernelCallSite(mi=mi, fi=fi, call=call)
    site.arg_exprs = list(outer.args) if outer is not None else None

    grid_expr = env.resolve(_kw(call, "grid"))
    in_specs_expr = _kw(call, "in_specs")
    out_specs_expr = _kw(call, "out_specs")
    scratch_expr = _kw(call, "scratch_shapes")

    gs = env.resolve(_kw(call, "grid_spec"))
    if isinstance(gs, ast.Call) and _last_name(gs.func) in (
            "PrefetchScalarGridSpec", "GridSpec"):
        npf = _int_const(env.resolve(_kw(gs, "num_scalar_prefetch"))
                         or ast.Constant(0))
        site.n_prefetch = npf or 0
        grid_expr = env.resolve(_kw(gs, "grid"))
        in_specs_expr = _kw(gs, "in_specs")
        out_specs_expr = _kw(gs, "out_specs")
        scratch_expr = _kw(gs, "scratch_shapes")

    grid_elts = _seq_elts(grid_expr) if grid_expr is not None else None
    site.grid_len = len(grid_elts) if grid_elts is not None else None
    site.grid_elts = list(grid_elts) if grid_elts is not None else None

    site.in_specs = _spec_list(in_specs_expr, mi, fi, env)
    site.out_specs = _spec_list(out_specs_expr, mi, fi, env)

    os_expr = env.resolve(_kw(call, "out_shape"))
    if os_expr is not None:
        elts = _seq_elts(os_expr)
        site.out_shapes = ([env.resolve(e) for e in elts]
                           if elts is not None else [os_expr])

    sc = env.resolve(scratch_expr)
    sc_elts = _seq_elts(sc) if sc is not None else None
    if sc_elts is not None:
        site.scratch = [env.resolve(e) for e in sc_elts]

    alias_expr = _kw(call, "input_output_aliases")
    if alias_expr is not None:
        site.has_alias_kw = True
        site.aliases = _alias_dict(env.resolve(alias_expr))

    _resolve_kernel(site, index, env)
    return site


def collect_kernel_calls(index: PackageIndex) -> List[KernelCallSite]:
    sites: List[KernelCallSite] = []
    for mi in index.modules.values():
        # map inner pallas_call Call -> outer invocation Call (the
        # `pl.pallas_call(...)(args)` idiom) so runtime args are visible
        outer_of: Dict[int, ast.Call] = {}
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Call):
                outer_of[id(node.func)] = node
        seen: Set[int] = set()
        for fi in mi.functions.values():
            for _, bare, call in fi.calls:
                if bare == "pallas_call" and id(call) not in seen:
                    seen.add(id(call))
                    sites.append(_parse_site(mi, fi, call,
                                             outer_of.get(id(call)), index))
        for node in walk_shallow(mi.tree):
            if isinstance(node, ast.Call) \
                    and _last_name(node.func) == "pallas_call" \
                    and id(node) not in seen:
                seen.add(id(node))
                sites.append(_parse_site(mi, None, node,
                                         outer_of.get(id(node)), index))
    sites.sort(key=lambda s: (s.mi.rel, s.line))
    return sites


# ---------------------------------------------------------------------------
# abstract interpretation over the grid domain
# ---------------------------------------------------------------------------

def _subscript_root(node: ast.AST) -> Optional[str]:
    while isinstance(node, ast.Subscript):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def unclamped_prefetch_reads(imap: IndexMapModel,
                             n_grid: Optional[int]) -> List[ast.AST]:
    """Scalar-prefetch table reads in an index_map that are not routed
    through any clamp call. Grid-id params are bounded by the grid domain
    ([0, grid[k]) by construction); a raw ``tab[i, j]`` read is the
    silent-OOB shape — the table may hold sentinel/-1 entries or garbage
    for dead slots, and Mosaic will DMA whatever address falls out."""
    if n_grid is None:
        # grid length unknown: assume every param beyond the block-rank
        # gap could be a table — be permissive (report nothing) rather
        # than guess wrong
        return []
    prefetch = set(imap.params[n_grid:])
    if not prefetch:
        return []
    offending: List[ast.AST] = []

    def visit(node: ast.AST, clamped: bool) -> None:
        if isinstance(node, ast.Call):
            inner_clamped = clamped or _last_name(node.func) in CLAMP_FUNCS
            for child in ast.iter_child_nodes(node):
                visit(child, inner_clamped)
            return
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
            for child in ast.iter_child_nodes(node):
                visit(child, True)
            return
        if isinstance(node, ast.Subscript) and not clamped:
            root = _subscript_root(node)
            if root in prefetch:
                offending.append(node)
                return  # don't double-report nested reads
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        for child in ast.iter_child_nodes(node):
            visit(child, clamped)

    for stmt in imap.body:
        visit(stmt, False)
    return offending


def negative_components(imap: IndexMapModel) -> List[ast.AST]:
    """Index_map return components that are literal negative ints —
    always out of the block-index domain."""
    out = []
    for comps in imap.returns:
        for c in comps:
            v = _int_const(c)
            if v is not None and v < 0:
                out.append(c)
    return out


def scratch_dtype_name(expr: ast.AST) -> Optional[str]:
    """dtype attribute of a ``pltpu.VMEM(shape, dtype)``-style scratch
    entry (None for semaphores / unresolved)."""
    if isinstance(expr, ast.Call) and _last_name(expr.func) in (
            "VMEM", "SMEM", "ANY") and len(expr.args) >= 2:
        return _last_name(expr.args[1])
    return None


def shape_dtype_struct(expr: ast.AST) -> Optional[Tuple[ast.AST, ast.AST]]:
    if isinstance(expr, ast.Call) \
            and _last_name(expr.func) == "ShapeDtypeStruct" \
            and len(expr.args) >= 2:
        return expr.args[0], expr.args[1]
    return None


# ---------------------------------------------------------------------------
# numeric transfer evaluation (ISSUE 11: the cost-model cross-check)
# ---------------------------------------------------------------------------
#
# The cost registry (`observability.costmodel`) states each kernel's HBM
# bytes in closed form; these helpers derive the same quantity from the
# committed BlockSpecs so the two can never drift apart silently.  The
# model is Pallas's fetch rule: a block is (re)copied at every grid step
# whose block index differs from the previous step's.  For an index_map
# that references grid dims S (directly or through body locals), over a
# lexicographic grid sweep the index changes whenever any dim at or
# outside max(S) ticks, so
#
#     fetch_runs = prod(grid[0 .. max(S)])        (1 when S is empty)
#
# and the spec's transfer is fetch_runs * block elements * dtype bytes.
# Specs with memory_space=ANY (manual-DMA operands) evaluate to None.

def eval_int_expr(node: Optional[ast.AST],
                  bindings: Dict[str, int]) -> Optional[int]:
    """Evaluate an integer shape expression under `bindings` (Name ->
    int). Supports the arithmetic the committed call sites use
    (+ - * // % **, unary -, min/max calls); None when anything else
    appears."""
    if node is None:
        return None
    if isinstance(node, ast.Constant):
        v = node.value
        return v if isinstance(v, int) and not isinstance(v, bool) else None
    if isinstance(node, ast.Name):
        return bindings.get(node.id)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = eval_int_expr(node.operand, bindings)
        return -v if v is not None else None
    if isinstance(node, ast.BinOp):
        a = eval_int_expr(node.left, bindings)
        b = eval_int_expr(node.right, bindings)
        if a is None or b is None:
            return None
        if isinstance(node.op, ast.Add):
            return a + b
        if isinstance(node.op, ast.Sub):
            return a - b
        if isinstance(node.op, ast.Mult):
            return a * b
        if isinstance(node.op, ast.FloorDiv):
            return a // b if b else None
        if isinstance(node.op, ast.Mod):
            return a % b if b else None
        if isinstance(node.op, ast.Pow):
            return a ** b
        return None
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("min", "max") and not node.keywords:
        vals = [eval_int_expr(a, bindings) for a in node.args]
        if any(v is None for v in vals) or not vals:
            return None
        return min(vals) if node.func.id == "min" else max(vals)
    return None


def grid_values(site: KernelCallSite,
                bindings: Dict[str, int]) -> Optional[List[int]]:
    """The concrete grid under `bindings`, or None when any component
    doesn't evaluate."""
    if site.grid_elts is None:
        return None
    out = []
    for e in site.grid_elts:
        v = eval_int_expr(e, bindings)
        if v is None:
            return None
        out.append(v)
    return out


def index_map_grid_refs(imap: IndexMapModel, grid_len: int) -> Set[int]:
    """Grid-dim positions the index map's return value depends on, with
    body locals expanded (the page maps return a clamped local `phys`
    computed from the grid id)."""
    local_defs: Dict[str, ast.AST] = {}
    for stmt in imap.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            local_defs[stmt.targets[0].id] = stmt.value

    names: Set[str] = set()
    pending = [c for comps in imap.returns for c in comps]
    seen_exprs = 0
    while pending and seen_exprs < 64:
        expr = pending.pop()
        seen_exprs += 1
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id not in names:
                names.add(n.id)
                if n.id in local_defs:
                    pending.append(local_defs[n.id])
    grid_params = imap.params[:grid_len]
    return {i for i, p in enumerate(grid_params) if p in names}


def spec_transfer_elems(spec: BlockSpecModel, grid: List[int],
                        grid_len: int,
                        bindings: Dict[str, int]) -> Optional[int]:
    """fetch_runs x block elements for one spec, or None when the spec
    stays in HBM (ANY), lacks a literal block shape, or an expression
    doesn't evaluate under `bindings`."""
    if spec.memory_space == "ANY" or spec.block_shape is None:
        return None
    elems = 1
    for e in spec.block_shape:
        v = eval_int_expr(e, bindings)
        if v is None:
            return None
        elems *= v
    if spec.index_map is None:
        return None
    refs = index_map_grid_refs(spec.index_map, grid_len)
    runs = 1
    if refs:
        last = max(refs)
        if last >= len(grid):
            return None
        for g in grid[: last + 1]:
            runs *= g
    return runs * elems


def transfer_bytes(site: KernelCallSite, bindings: Dict[str, int],
                   in_dtype_bytes: List[Optional[int]],
                   out_dtype_bytes: List[Optional[int]]
                   ) -> Optional[Dict[str, List[Optional[int]]]]:
    """{'in': [...], 'out': [...]} per-spec transfer bytes for a call
    site under concrete shape `bindings`; entries are None for specs
    that opt out (ANY space / unresolved), the dict is None when the
    grid itself doesn't evaluate.  Dtype bytes are supplied per spec
    (an entry of None skips that spec)."""
    if site.grid_len is None:
        return None
    grid = grid_values(site, bindings)
    if grid is None:
        return None

    def _side(specs, dtypes):
        out: List[Optional[int]] = []
        for i, spec in enumerate(specs or []):
            eb = dtypes[i] if i < len(dtypes) else None
            if eb is None:
                out.append(None)
                continue
            elems = spec_transfer_elems(spec, grid, site.grid_len,
                                        bindings)
            out.append(elems * eb if elems is not None else None)
        return out

    return {"in": _side(site.in_specs, in_dtype_bytes),
            "out": _side(site.out_specs, out_dtype_bytes)}
