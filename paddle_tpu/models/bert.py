"""BERT (ref capability: PaddleNLP paddlenlp/transformers/bert/modeling.py —
BertModel, BertForSequenceClassification; the SST-2 fine-tune baseline).

Architecture is standard post-LN BERT; attention runs through
nn.functional.scaled_dot_product_attention (flash-kernel routable).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .. import nn
from ..nn import functional as F
from ..nn import initializer as I

__all__ = ["BertConfig", "BertModel", "BertForSequenceClassification",
           "BertForPretraining", "bert_base_config", "bert_tiny_config"]


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, hidden_act="gelu",
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 max_position_embeddings=512, type_vocab_size=2,
                 initializer_range=0.02, layer_norm_eps=1e-12,
                 pad_token_id=0, num_labels=2):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.initializer_range = initializer_range
        self.layer_norm_eps = layer_norm_eps
        self.pad_token_id = pad_token_id
        self.num_labels = num_labels


def bert_base_config(**kw) -> BertConfig:
    return BertConfig(**kw)


def bert_tiny_config(**kw) -> BertConfig:
    base = dict(hidden_size=128, num_hidden_layers=2, num_attention_heads=2,
                intermediate_size=512, vocab_size=1024,
                max_position_embeddings=128)
    base.update(kw)
    return BertConfig(**base)


class BertEmbeddings(nn.Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        init = I.Normal(0.0, c.initializer_range)
        self.word_embeddings = nn.Embedding(c.vocab_size, c.hidden_size,
                                            padding_idx=c.pad_token_id)
        self.word_embeddings.weight._data = init(
            [c.vocab_size, c.hidden_size], "float32")
        self.position_embeddings = nn.Embedding(c.max_position_embeddings,
                                                c.hidden_size)
        self.token_type_embeddings = nn.Embedding(c.type_vocab_size,
                                                  c.hidden_size)
        self.layer_norm = nn.LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.dropout = nn.Dropout(c.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        S = input_ids.shape[1]
        if position_ids is None:
            position_ids = Tensor(jnp.arange(S, dtype=jnp.int32)[None, :])
        if token_type_ids is None:
            token_type_ids = Tensor(jnp.zeros_like(input_ids._data))
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class BertSelfAttention(nn.Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        self.num_heads = c.num_attention_heads
        self.head_dim = c.hidden_size // c.num_attention_heads
        self.query = nn.Linear(c.hidden_size, c.hidden_size)
        self.key = nn.Linear(c.hidden_size, c.hidden_size)
        self.value = nn.Linear(c.hidden_size, c.hidden_size)
        self.dropout_p = c.attention_probs_dropout_prob

    def forward(self, x, attn_mask=None):
        B, S, E = x.shape
        q = self.query(x).reshape([B, S, self.num_heads, self.head_dim])
        k = self.key(x).reshape([B, S, self.num_heads, self.head_dim])
        v = self.value(x).reshape([B, S, self.num_heads, self.head_dim])
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.dropout_p, training=self.training)
        return out.reshape([B, S, E])


class BertLayer(nn.Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        self.attention = BertSelfAttention(c)
        self.attn_out = nn.Linear(c.hidden_size, c.hidden_size)
        self.attn_norm = nn.LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.inter = nn.Linear(c.hidden_size, c.intermediate_size)
        self.output = nn.Linear(c.intermediate_size, c.hidden_size)
        self.out_norm = nn.LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.dropout = nn.Dropout(c.hidden_dropout_prob)
        self.act = c.hidden_act

    def forward(self, x, attn_mask=None):
        a = self.attention(x, attn_mask)
        x = self.attn_norm(x + self.dropout(self.attn_out(a)))
        h = getattr(F, self.act)(self.inter(x))
        x = self.out_norm(x + self.dropout(self.output(h)))
        return x


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = nn.LayerList(
            [BertLayer(config) for _ in range(config.num_hidden_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        mask = None
        if attention_mask is not None:
            # [B, S] 1/0 → additive [B, 1, 1, S]
            m = attention_mask._data.astype(jnp.float32)
            mask = Tensor((1.0 - m)[:, None, None, :] * -1e30)
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        for layer in self.encoder:
            x = layer(x, mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForSequenceClassification(nn.Layer):
    """The SST-2 fine-tune head (baseline config 1)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, config.num_labels)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.bert(input_ids, token_type_ids,
                              attention_mask=attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            loss = F.cross_entropy(logits, labels)
            return loss, logits
        return logits


class BertForPretraining(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.transform_norm = nn.LayerNorm(config.hidden_size)
        self.nsp = nn.Linear(config.hidden_size, 2)
        self.config = config

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, next_sentence_labels=None):
        seq, pooled = self.bert(input_ids, token_type_ids,
                                attention_mask=attention_mask)
        h = self.transform_norm(F.gelu(self.transform(seq)))
        # tied decoder: project back through the word embedding matrix
        emb = self.bert.embeddings.word_embeddings.weight
        mlm_logits = F.linear(h, emb.T)
        nsp_logits = self.nsp(pooled)
        if masked_lm_labels is not None:
            loss = F.cross_entropy(mlm_logits, masked_lm_labels,
                                   ignore_index=-100)
            if next_sentence_labels is not None:
                loss = loss + F.cross_entropy(nsp_logits, next_sentence_labels)
            return loss, mlm_logits, nsp_logits
        return mlm_logits, nsp_logits
