from . import autograd, dispatch, dtypes
from .tensor import Tensor, to_tensor

__all__ = ["Tensor", "to_tensor", "autograd", "dispatch", "dtypes"]
