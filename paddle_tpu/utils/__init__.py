"""paddle.utils parity (ref: python/paddle/utils/ — SURVEY §2.2 utils row):
run_check self-test, dlpack interop, cpp_extension (native builds),
deprecation decorator, unique_name."""

from __future__ import annotations

import itertools
import threading
import warnings
from typing import Optional

__all__ = ["run_check", "to_dlpack", "from_dlpack", "deprecated",
           "unique_name", "try_import", "cpp_extension"]


def run_check() -> None:
    """ref: paddle.utils.run_check — install self-test: single-device
    compute, then a multi-device SPMD program on whatever mesh exists."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..core.tensor import Tensor

    x = Tensor(jnp.ones((64, 64), jnp.float32))
    y = (x @ x).numpy()
    assert np.allclose(y, 64.0), "matmul self-test failed"
    n = jax.device_count()
    if n > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..distributed.mesh import build_hybrid_mesh
        mesh = build_hybrid_mesh(dp_degree=n)
        arr = jax.device_put(jnp.ones((n * 2, 8)),
                             NamedSharding(mesh, P("dp", None)))
        total = float(jnp.sum(arr * 2))
        assert total == n * 2 * 8 * 2
        print(f"PaddleTPU works well on {n} devices.")
    else:
        print("PaddleTPU works well on 1 device.")
    print("PaddleTPU is installed successfully!")


def to_dlpack(tensor):
    """Zero-copy export (ref: paddle.utils.dlpack.to_dlpack)."""
    from ..core.tensor import Tensor
    arr = tensor._data if isinstance(tensor, Tensor) else tensor
    # modern protocol: jax.Array implements __dlpack__ directly
    return arr.__dlpack__()


def from_dlpack(capsule):
    import jax
    from ..core.tensor import Tensor

    class _Holder:
        def __init__(self, c):
            self._c = c

        def __dlpack__(self, **kw):
            return self._c

        def __dlpack_device__(self):
            return (1, 0)  # kDLCPU

    src = capsule if hasattr(capsule, "__dlpack__") else _Holder(capsule)
    return Tensor(jax.dlpack.from_dlpack(src))


def deprecated(update_to: str = "", since: str = "", reason: str = ""):
    def deco(fn):
        def wrapped(*a, **kw):
            warnings.warn(
                f"{fn.__name__} is deprecated since {since}: {reason}. "
                f"Use {update_to} instead.", DeprecationWarning, stacklevel=2)
            return fn(*a, **kw)
        wrapped.__name__ = fn.__name__
        wrapped.__doc__ = fn.__doc__
        return wrapped
    return deco


class _UniqueName:
    def __init__(self):
        self._counters = {}
        self._lock = threading.Lock()

    def generate(self, key: str = "") -> str:
        with self._lock:
            c = self._counters.get(key, 0)
            self._counters[key] = c + 1
        return f"{key}_{c}" if key else str(c)

    def guard(self, new_generator=None):
        import contextlib

        @contextlib.contextmanager
        def g():
            saved = dict(self._counters)
            try:
                yield
            finally:
                self._counters = saved
        return g()


unique_name = _UniqueName()


def try_import(module_name: str, err_msg: Optional[str] = None):
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"{module_name} is required")


class cpp_extension:
    """ref: paddle.utils.cpp_extension — builds custom native ops. Here the
    JIT `load()` compiles a C++ translation unit with g++ into a shared
    library and returns a ctypes handle (the PD_BUILD_OP macro world is
    replaced by plain `extern "C"` symbols + jax custom_call/pure_callback
    registration on the python side)."""

    @staticmethod
    def load(name: str, sources, extra_cxx_flags=(), build_directory=None,
             verbose: bool = False):
        import ctypes
        import os
        import subprocess
        import tempfile
        build_dir = build_directory or tempfile.mkdtemp(prefix="pt_ext_")
        so = os.path.join(build_dir, f"{name}.so")
        cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared",
               *extra_cxx_flags, *sources, "-o", so]
        if verbose:
            print(" ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
        return ctypes.CDLL(so)

    class CppExtension:
        def __init__(self, sources, *a, **kw):
            self.sources = sources

    @staticmethod
    def setup(**kw):
        raise NotImplementedError(
            "setuptools-driven builds: use cpp_extension.load (JIT) — the "
            "wheel-time custom-op path is a packaging concern, not runtime")
