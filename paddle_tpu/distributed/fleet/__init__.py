"""fleet — hybrid-parallel sugar (ref: python/paddle/distributed/fleet/ —
fleet.init / distributed_model / distributed_optimizer, DistributedStrategy
hybrid_configs; SURVEY §2.3 P10).

TPU-native: fleet.init builds THE hybrid mesh and installs it as the current
mesh; distributed_model materializes parameters onto it per their sharding
specs (TP layers carry theirs; everything else replicates, with optional
ZeRO-style sharding of the fsdp axis); distributed_optimizer wires
cross-axis grad clip (trivial under GSPMD: the global norm is already
global). The user-facing vocabulary (dp_degree/mp_degree/pp_degree/
sharding_degree/sep_degree) is preserved verbatim.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ..mesh import HybridTopology, build_hybrid_mesh, get_mesh, set_mesh

__all__ = ["DistributedStrategy", "init", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "worker_index", "worker_num"]


class DistributedStrategy:
    """ref: fleet/base/distributed_strategy.py (protobuf-backed, ~80 knobs).
    Dataclass-style with the hybrid_configs vocabulary preserved."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
            "dcn_dp_degree": 1, "dcn_pp_degree": 1,
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.find_unused_parameters = False


_fleet_state = {"topology": None, "strategy": None}


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None, log_level=None):
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    mesh = build_hybrid_mesh(
        dp_degree=hc.get("dp_degree", 1), mp_degree=hc.get("mp_degree", 1),
        pp_degree=hc.get("pp_degree", 1),
        sharding_degree=hc.get("sharding_degree", 1),
        sep_degree=hc.get("sep_degree", 1),
        dcn_dp_degree=hc.get("dcn_dp_degree", 1),
        dcn_pp_degree=hc.get("dcn_pp_degree", 1))
    set_mesh(mesh)
    _fleet_state["topology"] = HybridTopology(mesh)
    _fleet_state["strategy"] = strategy
    return mesh


def get_hybrid_communicate_group() -> HybridTopology:
    return _fleet_state["topology"]


def worker_index() -> int:
    return jax.process_index()


def worker_num() -> int:
    return jax.process_count()


def distributed_model(model, shard_params_on: Optional[str] = None):
    """Materialize every parameter/buffer on the hybrid mesh.

    - parameters carrying `_sharding_spec` (TP layers) use it;
    - `shard_params_on="sharding"` additionally ZeRO-3-shards otherwise-
      replicated parameters' dim 0 on the sharding axis (P3 parity — on TPU
      this IS group_sharded_parallel level p_g_os: a spec choice);
    - everything else replicates.
    """
    mesh = get_mesh()
    if mesh is None:
        raise RuntimeError("call fleet.init(strategy) first")
    for name, sub in model.named_sublayers(include_self=True):
        for pname, p in list(sub.__dict__["_parameters"].items()):
            if p is None:
                continue
            spec = getattr(p, "_sharding_spec", None)
            if spec is None:
                if (shard_params_on and mesh.shape.get(shard_params_on, 1) > 1
                        and p.ndim > 0
                        and p._data.shape[0] % mesh.shape[shard_params_on] == 0):
                    spec = P(shard_params_on)
                else:
                    spec = P()
            from ..mesh import sanitize_spec
            spec = sanitize_spec(mesh, spec)
            p._data = jax.device_put(p._data, NamedSharding(mesh, spec))
        for bname, b in sub.__dict__["_buffers"].items():
            if b is not None:
                b._data = jax.device_put(b._data, NamedSharding(mesh, P()))
    return model


def distributed_optimizer(optimizer, strategy=None):
    """ref: HybridParallelOptimizer — on TPU the global-norm clip is already
    global under GSPMD (grads live on the mesh), so the optimizer passes
    through; optimizer state inherits each param's sharding lazily on first
    step (accumulators are created from the param's sharded buffer)."""
    return optimizer


from . import utils  # noqa: F401,E402  (fleet.utils parity)
