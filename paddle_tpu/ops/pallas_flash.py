"""In-tree flash attention kernel (fwd + bwd), authored and tunable.

Reference capability: FlashAttention2 fwd/bwd —
paddle/phi/kernels/gpu/flash_attn_kernel.cu and
python/paddle/nn/functional/flash_attention.py (VERDICT r2 item 9: own
the kernel the serving/pretrain benches spend their time in, instead of
wrapping jax.experimental.pallas.ops.tpu.flash_attention).

Same machinery as ops/pallas_flashmask.py (that kernel proved the
pattern; this one drops the band encodings and adds what the bundled
kernel refuses):

  - causal with UNEQUAL Sq/Sk, bottom-right aligned: query row i sees
    key j iff j <= i + (Sk - Sq) — exactly sdpa_reference's
    jnp.tril(..., k=Sk-Sq) convention, so the composite stays the oracle;
  - optional q/kv segment ids (varlen packing, key-padding routing) as
    an elementwise block-local mask;
  - block-level skip for fully-above-diagonal blocks, computed from
    program ids (static — no skip-map array needed);
  - online-softmax forward emitting logsumexp; flash-style backward
    (dq sweep over k blocks, dk/dv sweep over q blocks);
  - caller-tunable block sizes (default 128x128), f32 accumulation,
    interpret mode off-TPU so the CPU suite covers the kernel logic.

Fully-hidden query rows (causal offset < 0 at the sequence head, or an
unmatched segment) produce zero output and a +1e30 lse sentinel, so the
backward underflows to zero instead of producing NaN.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; accept both
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

__all__ = ["flash_sdpa", "flash_kernel_eligible"]

_NEG = -1e30

# B/H/outer-block grid dims are independent; only the innermost dim
# carries the online-softmax / accumulator state. Marking them parallel
# lets Mosaic split them across TensorCores (megacore parts)
_CPARAMS = _CompilerParams(
    dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _mask_for_block(qi, kj, bq, bk, causal, off, use_seg, sq_ref, sk_ref):
    """[bq, bk] bool mask of HIDDEN entries for this block."""
    masked = None
    if causal:
        rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        masked = cols > rows + off
    if use_seg:
        seg = sq_ref[0, 0][:, None] != sk_ref[0, 0][None, :]
        masked = seg if masked is None else jnp.logical_or(masked, seg)
    return masked


def _block_visible(qi, kj, bq, bk, off):
    """Causal block skip: the block's lowest row sees its first column?"""
    return kj * bk <= qi * bq + (bq - 1) + off


def _fwd_kernel(sq_ref, sk_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, bq, bk, causal, off,
                use_seg):
    kj = pl.program_id(3)
    nk = pl.num_programs(3)
    qi = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    visible = _block_visible(qi, kj, bq, bk, off) if causal \
        else (kj == kj)

    @pl.when(visible)
    def _compute():
        q = q_ref[0, 0]                                       # [bq, D]
        k = k_ref[0, 0]                                       # [bk, D]
        # inputs stay bf16 on the MXU (full throughput); accumulation is
        # f32 via preferred_element_type — same contract as the bundled
        # kernel (casting inputs to f32 halves MXU throughput)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bq, bk]
        masked = _mask_for_block(qi, kj, bq, bk, causal, off, use_seg,
                                 sq_ref, sk_ref)
        if masked is not None:
            s = jnp.where(masked, _NEG, s)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        p = jnp.exp(s - m_new)
        if masked is not None:
            p = jnp.where(masked, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, -1, keepdims=True)
        v = v_ref[0, 0]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(kj == nk - 1)
    def _emit():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.where(
            l == 0.0, -_NEG, m_ref[:] + jnp.log(l_safe))


def _bwd_dq_kernel(sq_ref, sk_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   di_ref, dq_ref, dq_acc, *, scale, bq, bk, causal, off,
                   use_seg):
    kj = pl.program_id(3)
    nk = pl.num_programs(3)
    qi = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    visible = _block_visible(qi, kj, bq, bk, off) if causal \
        else (kj == kj)

    @pl.when(visible)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        masked = _mask_for_block(qi, kj, bq, bk, causal, off, use_seg,
                                 sq_ref, sk_ref)
        p = jnp.exp(s - lse_ref[0, 0])
        if masked is not None:
            p = jnp.where(masked, 0.0, p)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - di_ref[0, 0]) * scale).astype(k.dtype)
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _emit():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(sq_ref, sk_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    di_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, scale, bq,
                    bk, causal, off, use_seg):
    qi = pl.program_id(3)
    nq = pl.num_programs(3)
    kj = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    visible = _block_visible(qi, kj, bq, bk, off) if causal \
        else (qi == qi)

    @pl.when(visible)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bq, bk]
        masked = _mask_for_block(qi, kj, bq, bk, causal, off, use_seg,
                                 sq_ref, sk_ref)
        p = jnp.exp(s - lse_ref[0, 0])
        if masked is not None:
            p = jnp.where(masked, 0.0, p)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bk, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bq, bk]
        ds = (p * (dp - di_ref[0, 0]) * scale).astype(q.dtype)
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bk, D]

    @pl.when(qi == nq - 1)
    def _emit():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _specs(bq, bk, D, order: str):
    """in_specs for (seg_q, seg_kv, q, k, v). order='qk': grid
    (B, H, nq, nk) with q indexed by i; order='kq': grid (B, H, nk, nq)
    with q indexed by j (the dkv sweep)."""
    if order == "qk":
        sqmap = lambda b, h, i, j: (b, 0, i)
        skmap = lambda b, h, i, j: (b, 0, j)
        qmap = lambda b, h, i, j: (b, h, i, 0)
        kmap = lambda b, h, i, j: (b, h, j, 0)
    else:
        sqmap = lambda b, h, i, j: (b, 0, j)
        skmap = lambda b, h, i, j: (b, 0, i)
        qmap = lambda b, h, i, j: (b, h, j, 0)
        kmap = lambda b, h, i, j: (b, h, i, 0)
    # segment ids ride as [B, 1, S] so the (1, 1, blk) block satisfies the
    # Mosaic trailing-dims rule (second-to-last block dim == full dim 1)
    return ([pl.BlockSpec((1, 1, bq), sqmap),
             pl.BlockSpec((1, 1, bk), skmap),
             pl.BlockSpec((1, 1, bq, D), qmap),
             pl.BlockSpec((1, 1, bk, D), kmap),
             pl.BlockSpec((1, 1, bk, D), kmap)], qmap, kmap)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_core(q, k, v, seg_q, seg_kv, scale, causal, bq, bk, use_seg):
    o, _ = _flash_fwd_impl(q, k, v, seg_q, seg_kv, scale, causal, bq, bk,
                           use_seg)
    return o


def _flash_fwd_impl(q, k, v, seg_q, seg_kv, scale, causal, bq, bk,
                    use_seg):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    off = Sk - Sq
    nq, nk = Sq // bq, Sk // bk
    in_specs, qmap, _ = _specs(bq, bk, D, "qk")
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, bq=bq, bk=bk,
                          causal=causal, off=off, use_seg=use_seg),
        grid=(B, H, nq, nk),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, 1, bq, D), qmap),
                   pl.BlockSpec((1, 1, bq, 1),
                                lambda b, h, i, j: (b, h, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
                   jax.ShapeDtypeStruct((B, H, Sq, 1), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32),
                        pltpu.VMEM((bq, 1), jnp.float32),
                        pltpu.VMEM((bq, 1), jnp.float32)],
        compiler_params=_CPARAMS,
        interpret=_interpret(),
    )(seg_q, seg_kv, q, k, v)
    return o, lse


def _flash_vjp_fwd(q, k, v, seg_q, seg_kv, scale, causal, bq, bk,
                   use_seg):
    o, lse = _flash_fwd_impl(q, k, v, seg_q, seg_kv, scale, causal, bq,
                             bk, use_seg)
    return o, (q, k, v, seg_q, seg_kv, o, lse)


def _flash_vjp_bwd(scale, causal, bq, bk, use_seg, res, do):
    q, k, v, seg_q, seg_kv, o, lse = res
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    off = Sk - Sq
    di = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                 axis=-1, keepdims=True)                     # [B,H,Sq,1]
    nq, nk = Sq // bq, Sk // bk

    in_specs, qmap, kmap = _specs(bq, bk, D, "qk")
    row_spec = pl.BlockSpec((1, 1, bq, 1),
                            lambda b, h, i, j: (b, h, i, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, bq=bq, bk=bk,
                          causal=causal, off=off, use_seg=use_seg),
        grid=(B, H, nq, nk),
        in_specs=in_specs + [pl.BlockSpec((1, 1, bq, D), qmap),
                             row_spec, row_spec],
        out_specs=pl.BlockSpec((1, 1, bq, D), qmap),
        out_shape=jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=_CPARAMS,
        interpret=_interpret(),
    )(seg_q, seg_kv, q, k, v, do, lse, di)

    in_specs2, qmap2, kmap2 = _specs(bq, bk, D, "kq")
    row_spec2 = pl.BlockSpec((1, 1, bq, 1),
                             lambda b, h, i, j: (b, h, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, bq=bq, bk=bk,
                          causal=causal, off=off, use_seg=use_seg),
        grid=(B, H, nk, nq),
        in_specs=in_specs2 + [pl.BlockSpec((1, 1, bq, D), qmap2),
                              row_spec2, row_spec2],
        out_specs=[pl.BlockSpec((1, 1, bk, D), kmap2),
                   pl.BlockSpec((1, 1, bk, D), kmap2)],
        out_shape=[jax.ShapeDtypeStruct((B, H, Sk, D), k.dtype),
                   jax.ShapeDtypeStruct((B, H, Sk, D), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        compiler_params=_CPARAMS,
        interpret=_interpret(),
    )(seg_q, seg_kv, q, k, v, do, lse, di)
    return dq, dk, dv, None, None


_flash_core.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_kernel_eligible(Sq: int, Sk: int, D: int, block_q: int = 128,
                          block_k: int = 128) -> bool:
    """Unlike the bundled kernel's gate, causal Sq != Sk IS eligible."""
    return (Sq % block_q == 0 and Sk % block_k == 0
            and (D % 128 == 0 or (D <= 128 and D % 64 == 0)))


def flash_sdpa(q, k, v, causal: bool = False, segment_ids_q=None,
               segment_ids_kv=None, scale: Optional[float] = None,
               block_q: int = 512, block_k: int = 512):
    """[B,S,H,D] flash attention through the in-tree kernel. Causal is
    bottom-right aligned for Sq != Sk (sdpa_reference convention).
    Differentiable (flash-style bwd kernels). Default 512x512 blocks
    (tools/flash_bench.py sweep on the v5e: 512-class blocks beat 128 by
    ~1.2-1.7x at seq >= 4096); blocks clamp to the sequence lengths so
    short sequences still run."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    if Sq % block_q or Sk % block_k:
        raise ValueError(
            f"flash_sdpa: Sq={Sq}/Sk={Sk} not divisible by blocks "
            f"{block_q}x{block_k} (see flash_kernel_eligible)")
    if scale is None:
        scale = D ** -0.5
    use_seg = segment_ids_q is not None or segment_ids_kv is not None
    if use_seg:
        seg_q = (segment_ids_q if segment_ids_q is not None
                 else jnp.ones((B, Sq))).astype(jnp.int32)
        seg_kv = (segment_ids_kv if segment_ids_kv is not None
                  else jnp.ones((B, Sk))).astype(jnp.int32)
    else:
        # placeholders keep the kernel signature static; use_seg=False
        # compiles the masking out entirely
        seg_q = jnp.zeros((B, Sq), jnp.int32)
        seg_kv = jnp.zeros((B, Sk), jnp.int32)
    seg_q = seg_q[:, None, :]                 # [B, 1, S]: see _specs
    seg_kv = seg_kv[:, None, :]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    out = _flash_core(qh, kh, vh, seg_q, seg_kv, float(scale),
                      bool(causal), block_q, block_k, use_seg)
    return jnp.swapaxes(out, 1, 2)


# certification (ROADMAP item 5 / paddlelint PK105): the dense-softmax
# composite is the oracle; lazy string — flash_attention imports us
from .oracles import register_oracle  # noqa: E402

register_oracle(
    "flash_sdpa", kernel=flash_sdpa,
    reference="paddle_tpu.ops.flash_attention:sdpa_reference",
    parity_test="tests/test_flash_kernel.py::TestForwardParity")
