"""Worker for the launcher-driven MULTI-PROCESS run_pretrain test: the
literal reference workflow (distributed launch -> run_pretrain.py) on 2
simulated hosts x 4 CPU devices. mh_bootstrap joins the jax pod before
any backend init; run_pretrain then sees the GLOBAL 8-device mesh and
its sharded-checkpoint writer tags shards per process."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import mh_bootstrap  # noqa: F401  (env + jax.distributed init, pre-jax)

from paddle_tpu.trainer.run_pretrain import main  # noqa: E402

sys.exit(main(["--config", os.environ["MH_CFG"]]))
