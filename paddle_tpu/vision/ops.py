"""paddle.vision.ops parity: detection operators (ref: python/paddle/
vision/ops.py over CUDA kernels roi_align/nms/deform_conv — SURVEY §2.2
vision row "GPU-accelerated ops").

TPU-native mechanism notes:
- roi_align / roi_pool: bilinear/max sampling expressed as dense gathers —
  XLA lowers to vectorized dynamic-slices; no atomics needed (the CUDA
  kernels' main complication).
- nms: O(N²) IoU matrix + a greedy suppression sweep under lax.fori_loop —
  compiler-friendly fixed-shape loop; the final index extraction is
  data-dependent and therefore eager-only (like every NMS).
- deform_conv2d: offset-shifted bilinear sampling (gather) followed by ONE
  im2col-style matmul on the MXU — the idiomatic TPU shape for DCN.

Layouts follow paddle: images NCHW, boxes [N, 4] as (x1, y1, x2, y2).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor

__all__ = ["nms", "roi_align", "roi_pool", "deform_conv2d", "DeformConv2D"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


# ---------------------------------------------------------------------------
# NMS
# ---------------------------------------------------------------------------
def _iou_matrix(boxes):
    x1, y1, x2, y2 = (boxes[:, i] for i in range(4))
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """ref: paddle.vision.ops.nms. Greedy suppression in score order;
    category-aware when category_idxs is given (boxes of different
    categories never suppress each other). Returns kept indices (Tensor,
    int64-ordered by score) — data-dependent size, eager-only."""
    b = _arr(boxes).astype(jnp.float32)
    n = b.shape[0]
    s = jnp.arange(n, 0, -1, jnp.float32) if scores is None \
        else _arr(scores).astype(jnp.float32)
    iou = _iou_matrix(b)
    if category_idxs is not None:
        cat = _arr(category_idxs)
        same = cat[:, None] == cat[None, :]
        iou = jnp.where(same, iou, 0.0)
    order = jnp.argsort(-s)

    def body(i, keep):
        bi = order[i]
        # suppressed iff a higher-scoring KEPT box overlaps > threshold
        higher = jnp.arange(n) < i
        sup = jnp.any(higher & keep[order] & (iou[bi, order] > iou_threshold))
        return keep.at[bi].set(~sup)

    keep = jax.lax.fori_loop(0, n, body, jnp.zeros((n,), bool))
    kept_sorted = order[keep[order]]  # score order, eager extraction
    if top_k is not None:
        kept_sorted = kept_sorted[:top_k]
    return Tensor(kept_sorted.astype(jnp.int64))


# ---------------------------------------------------------------------------
# RoI align / pool
# ---------------------------------------------------------------------------
def _bilinear(feat, y, x):
    """feat [C, H, W]; y/x sample grids of any shape → [C, *grid]."""
    H, W = feat.shape[-2:]
    y = jnp.clip(y, 0.0, H - 1.0)
    x = jnp.clip(x, 0.0, W - 1.0)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    wy = y - y0
    wx = x - x0
    v00 = feat[:, y0, x0]
    v01 = feat[:, y0, x1]
    v10 = feat[:, y1, x0]
    v11 = feat[:, y1, x1]
    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
            + v10 * wy * (1 - wx) + v11 * wy * wx)


def _bilinear_zero(feat, y, x):
    """Bilinear sampling with ZERO padding outside the image (the
    deform-conv reference semantics; `_bilinear` edge-clamps instead,
    which is what roi_align wants)."""
    H, W = feat.shape[-2:]
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    wy = y - y0
    wx = x - x0
    out = 0.0
    for yc, ww_y in ((y0, 1 - wy), (y0 + 1, wy)):
        for xc, ww_x in ((x0, 1 - wx), (x0 + 1, wx)):
            valid = (yc >= 0) & (yc < H) & (xc >= 0) & (xc < W)
            v = feat[:, jnp.clip(yc, 0, H - 1), jnp.clip(xc, 0, W - 1)]
            out = out + v * (ww_y * ww_x * valid)
    return out


def _roi_grid(box, pooled: Tuple[int, int], spatial_scale, sr_h, sr_w,
              aligned):
    ph, pw = pooled
    off = 0.5 if aligned else 0.0
    x1 = box[0] * spatial_scale - off
    y1 = box[1] * spatial_scale - off
    x2 = box[2] * spatial_scale - off
    y2 = box[3] * spatial_scale - off
    rw = x2 - x1
    rh = y2 - y1
    if not aligned:
        rw = jnp.maximum(rw, 1.0)
        rh = jnp.maximum(rh, 1.0)
    bin_h = rh / ph
    bin_w = rw / pw
    iy = (jnp.arange(sr_h) + 0.5) / sr_h
    ix = (jnp.arange(sr_w) + 0.5) / sr_w
    ys = y1 + (jnp.arange(ph)[:, None] + iy[None, :]) * bin_h  # [ph, sr_h]
    xs = x1 + (jnp.arange(pw)[:, None] + ix[None, :]) * bin_w  # [pw, sr_w]
    return ys, xs


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """ref: paddle.vision.ops.roi_align. boxes [R,4] concatenated over the
    batch, boxes_num [N] giving the per-image count. sampling_ratio<=0
    means reference-adaptive: ceil(roi_size/bin_count) samples per bin,
    computed per ROI (host-side — boxes are data, so eager-only)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    import numpy as np
    xb = _arr(x)
    bx = _arr(boxes).astype(jnp.float32)
    bn = [int(v) for v in jnp.asarray(_arr(boxes_num))]
    img_idx = [i for i, c in enumerate(bn) for _ in range(c)]
    ph, pw = output_size
    bx_np = np.asarray(bx)
    srs = []
    for r in range(bx_np.shape[0]):
        if sampling_ratio > 0:
            srs.append((sampling_ratio, sampling_ratio))
        else:
            rh = (bx_np[r, 3] - bx_np[r, 1]) * spatial_scale
            rw = (bx_np[r, 2] - bx_np[r, 0]) * spatial_scale
            srs.append((max(int(math.ceil(rh / ph)), 1),
                        max(int(math.ceil(rw / pw)), 1)))

    def impl(feat_all):
        outs = []
        for r in range(bx_np.shape[0]):
            feat = feat_all[img_idx[r]]
            sr_h, sr_w = srs[r]
            ys, xs = _roi_grid(bx[r], (ph, pw), spatial_scale, sr_h, sr_w,
                               aligned)
            Y, X = jnp.meshgrid(ys.reshape(-1), xs.reshape(-1),
                                indexing="ij")
            vals = _bilinear(feat, Y, X)
            C = feat.shape[0]
            vals = vals.reshape(C, ph, sr_h, pw, sr_w)
            outs.append(vals.mean(axis=(2, 4)))
        return jnp.stack(outs)

    return apply("roi_align", impl, [x if isinstance(x, Tensor)
                                     else Tensor(xb)])


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """ref: paddle.vision.ops.roi_pool (max pooling over quantized bins)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    xb = _arr(x)
    bx = _arr(boxes).astype(jnp.float32)
    bn = [int(v) for v in jnp.asarray(_arr(boxes_num))]
    img_idx = jnp.asarray(
        sum(([i] * c for i, c in enumerate(bn)), []), jnp.int32)
    ph, pw = output_size
    H, W = xb.shape[-2:]

    def impl(feat_all):
        def one(box, img):
            feat = feat_all[img]
            x1 = jnp.round(box[0] * spatial_scale).astype(jnp.int32)
            y1 = jnp.round(box[1] * spatial_scale).astype(jnp.int32)
            x2 = jnp.round(box[2] * spatial_scale).astype(jnp.int32)
            y2 = jnp.round(box[3] * spatial_scale).astype(jnp.int32)
            rh = jnp.maximum(y2 - y1 + 1, 1)
            rw = jnp.maximum(x2 - x1 + 1, 1)
            # dense mask-max over the full feature map per bin (TPU-style:
            # trade FLOPs for gather-free regular compute)
            ys = jnp.arange(H)[None, :]
            xs = jnp.arange(W)[None, :]
            b_y0 = y1 + (jnp.arange(ph)[:, None] * rh) // ph
            b_y1 = y1 + ((jnp.arange(ph)[:, None] + 1) * rh + ph - 1) // ph
            b_x0 = x1 + (jnp.arange(pw)[:, None] * rw) // pw
            b_x1 = x1 + ((jnp.arange(pw)[:, None] + 1) * rw + pw - 1) // pw
            my = (ys >= b_y0) & (ys < jnp.maximum(b_y1, b_y0 + 1))  # [ph,H]
            mx = (xs >= b_x0) & (xs < jnp.maximum(b_x1, b_x0 + 1))  # [pw,W]
            m = my[:, None, :, None] & mx[None, :, None, :]  # [ph,pw,H,W]
            neg = jnp.asarray(-3.4e38, feat.dtype)
            v = jnp.where(m[None], feat[:, None, None, :, :], neg)
            mx = v.max(axis=(-1, -2))
            # empty bin (box off the feature map / degenerate) → 0, the
            # reference's convention — never the -3.4e38 sentinel
            return jnp.where(m.any(axis=(-1, -2))[None], mx, 0.0)
        return jax.vmap(one)(bx, img_idx)

    return apply("roi_pool", impl, [x if isinstance(x, Tensor)
                                    else Tensor(xb)])


# ---------------------------------------------------------------------------
# Deformable convolution (DCNv1/v2)
# ---------------------------------------------------------------------------
def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """ref: paddle.vision.ops.deform_conv2d. x NCHW, offset
    [N, 2·dg·kh·kw, Ho, Wo] ((dy, dx) interleaved per kernel point), mask
    [N, dg·kh·kw, Ho, Wo] for DCNv2. groups/deformable_groups=1 supported.
    """
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError("groups/deformable_groups > 1")
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph_, pw_ = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation
    wshape = (_arr(weight)).shape
    oc, ic, kh, kw = wshape
    xb = _arr(x)
    N, C, H, W = xb.shape
    Ho = (H + 2 * ph_ - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw_ - (dw * (kw - 1) + 1)) // sw + 1

    base_y = jnp.arange(Ho) * sh - ph_
    base_x = jnp.arange(Wo) * sw - pw_
    ky = jnp.arange(kh) * dh
    kx = jnp.arange(kw) * dw

    def impl(xa, off, w, *rest):
        i = 0
        m = None
        if mask is not None:
            m = rest[0].reshape(N, kh, kw, Ho, Wo)
            i = 1
        b = rest[i] if bias is not None else None
        offr = off.reshape(N, kh, kw, 2, Ho, Wo)
        dy = offr[:, :, :, 0]
        dx = offr[:, :, :, 1]
        # sample positions [N, kh, kw, Ho, Wo]
        yy = (base_y[None, None, None, :, None]
              + ky[None, :, None, None, None] + dy)
        xx = (base_x[None, None, None, None, :]
              + kx[None, None, :, None, None] + dx)
        vals = jax.vmap(_bilinear_zero)(xa, yy, xx)  # [N,C,kh,kw,Ho,Wo]
        if m is not None:
            vals = vals * m[:, None]
        # im2col contraction: one MXU einsum over (c, kh, kw)
        out = jnp.einsum("ncijhw,ocij->nohw", vals, w)
        if b is not None:
            out = out + b[None, :, None, None]
        return out

    inputs = [x if isinstance(x, Tensor) else Tensor(xb),
              offset if isinstance(offset, Tensor) else Tensor(_arr(offset)),
              weight if isinstance(weight, Tensor) else Tensor(_arr(weight))]
    if mask is not None:
        inputs.append(mask if isinstance(mask, Tensor)
                      else Tensor(_arr(mask)))
    if bias is not None:
        inputs.append(bias if isinstance(bias, Tensor)
                      else Tensor(_arr(bias)))
    return apply("deform_conv2d", impl, inputs)


from ..nn import Layer as _Layer  # noqa: E402
from ..nn import initializer as _I  # noqa: E402


class DeformConv2D(_Layer):
    """ref: paddle.vision.ops.DeformConv2D. A real nn.Layer so enclosing
    models pick up weight/bias in parameters() and state_dict."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.deformable_groups, self.groups = deformable_groups, groups
        fan_in = in_channels * ks[0] * ks[1]
        std = math.sqrt(2.0 / fan_in)
        self.weight = self.create_parameter(
            [out_channels, in_channels, ks[0], ks[1]],
            default_initializer=_I.Normal(0.0, std))
        if bias_attr is not False:
            self.bias = self.create_parameter([out_channels], is_bias=True,
                                              attr=bias_attr)
        else:
            self.bias = None

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             stride=self.stride, padding=self.padding,
                             dilation=self.dilation,
                             deformable_groups=self.deformable_groups,
                             groups=self.groups, mask=mask)
