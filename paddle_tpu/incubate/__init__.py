"""paddle.incubate parity package (ref: python/paddle/incubate/).

Hosts the fused-op wrappers and the MoE stack (ref:
python/paddle/incubate/distributed/models/moe/ — SURVEY §2.2 incubate row,
§2.3 P7).
"""

from . import moe  # noqa: F401
from . import nn  # noqa: F401

from . import asp  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import DistributedFusedLamb, LookAhead  # noqa: F401

__all__ = ["moe", "nn", "asp", "optimizer", "LookAhead",
           "DistributedFusedLamb"]
