"""Functional optimizer core (ref: paddle/phi/kernels/gpu/adamw_kernel.cu —
the fused AdamW update; python/paddle/optimizer/adamw.py for semantics).

The per-tensor `adamw_kernel` is THE AdamW math for the whole framework:
the eager `optimizer.AdamW.step()` path and the jitted SPMD pretrain step
(trainer/pretrain.py) both call it, so the flagship benchmark exercises the
product's optimizer rather than a bespoke re-implementation. Tree-level
`FunctionalAdamW` packages it as a pure (grads, state, params) -> (params,
state) transform whose state inherits the params' shardings — the TPU analog
of the reference's multi-tensor fused optimizer sweep.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp

__all__ = ["adamw_kernel", "global_norm", "clip_tree_by_global_norm",
           "AdamWState", "FunctionalAdamW"]


def adamw_kernel(w, g, m, v, t, *, lr, b1, b2, eps, weight_decay,
                 do_decay=True, vmax=None):
    """One decoupled-weight-decay Adam update in f32 master precision.

    t is the 1-based step AFTER this update (bias correction uses it).
    Returns (new_w, new_m, new_v), plus new_vmax when vmax is given
    (amsgrad: the denominator uses the running max of vhat).
    """
    g = g.astype(w.dtype)
    if do_decay:
        w = w * (1.0 - lr * weight_decay)
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * jnp.square(g)
    mhat = m / (1.0 - b1 ** t)
    vhat = v / (1.0 - b2 ** t)
    if vmax is not None:
        vmax = jnp.maximum(vmax, vhat)
        return w - lr * mhat / (jnp.sqrt(vmax) + eps), m, v, vmax
    return w - lr * mhat / (jnp.sqrt(vhat) + eps), m, v


def global_norm(grads: Any) -> jnp.ndarray:
    """f32 global l2 norm over a pytree of gradients."""
    leaves = [g for g in jax.tree.leaves(grads) if g is not None]
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in leaves))


def clip_tree_by_global_norm(grads: Any, clip_norm: float):
    """ClipGradByGlobalNorm semantics (nn/clip.py): scale by
    clip_norm / max(norm, clip_norm). Returns (clipped, norm)."""
    norm = global_norm(grads)
    scale = clip_norm / jnp.maximum(norm, clip_norm)
    return jax.tree.map(
        lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads), norm


class AdamWState(NamedTuple):
    moment1: Any
    moment2: Any
    count: jnp.ndarray  # int32 scalar, number of updates applied


class FunctionalAdamW:
    """Pure-tree AdamW with master-precision state, global-norm clipping and
    an optional jnp-traceable LR schedule (lr may be a float or a fn
    step -> scalar, e.g. optimizer.lr schedules' traceable forms)."""

    def __init__(self, learning_rate: Union[float, Callable] = 1e-3,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8, weight_decay: float = 0.01,
                 clip_norm: Optional[float] = None,
                 decay_mask: Optional[Any] = None,
                 moment_dtype=jnp.float32):
        self.lr = learning_rate
        self.b1, self.b2, self.eps = beta1, beta2, epsilon
        self.weight_decay = weight_decay
        self.clip_norm = clip_norm
        # decay_mask: optional pytree of bools (same structure as params);
        # None = decay everything (paddle AdamW default)
        self.decay_mask = decay_mask
        # moment_dtype=bfloat16 halves optimizer-state HBM (the lever
        # that admits a larger per-chip batch); the update itself stays
        # f32 — moments are up-cast in, rounded on store
        self.moment_dtype = jnp.dtype(moment_dtype)
        if self.moment_dtype != jnp.float32 and beta2 > 0.99:
            # round-to-nearest bf16 can't represent a (1-b2) < 1% EMA
            # step: v rounds back to its previous value every update and
            # the second moment FREEZES. b2 <= 0.99 keeps the per-step
            # change above bf16's half-ulp.
            raise ValueError(
                f"moment_dtype={self.moment_dtype} with beta2={beta2}: "
                f"the second-moment EMA stalls under bf16 rounding when "
                f"beta2 > 0.99; lower beta2 or keep float32 moments")

    def init(self, params: Any) -> AdamWState:
        mdt = self.moment_dtype
        leaves, treedef = jax.tree.flatten(params)
        if any(isinstance(l, jax.core.Tracer) for l in leaves):
            m = [jnp.zeros_like(l, dtype=mdt) for l in leaves]
            v = [jnp.zeros_like(l, dtype=mdt) for l in leaves]
        else:
            # allocate both moment trees ON DEVICE in one compiled
            # program: no host->device transfer of gigabytes of zeros
            # and no per-(shape,sharding) compile — for billion-param
            # trees this is minutes faster than device_put of np zeros
            shapes = [l.shape for l in leaves]
            shardings = [getattr(l, "sharding", None) for l in leaves]
            mk = jax.jit(
                lambda: tuple([jnp.zeros(s, mdt) for s in shapes]
                              for _ in range(2)),
                out_shardings=(shardings, shardings)
                if all(s is not None for s in shardings) else None)
            m, v = mk()
        return AdamWState(moment1=jax.tree.unflatten(treedef, m),
                          moment2=jax.tree.unflatten(treedef, v),
                          count=jnp.zeros((), jnp.int32))

    def lr_at(self, count) -> jnp.ndarray:
        return self.lr(count) if callable(self.lr) else jnp.asarray(
            self.lr, jnp.float32)

    def update(self, grads: Any, state: AdamWState, params: Any):
        """-> (new_params, new_state, grad_norm). params are the f32 master
        weights; the caller owns the bf16 compute-cast (amp O2)."""
        if self.clip_norm is not None:
            grads, norm = clip_tree_by_global_norm(grads, self.clip_norm)
        else:
            norm = global_norm(grads)
        count = state.count + 1
        t = count.astype(jnp.float32)
        lr = self.lr_at(count)
        # the update math runs in f32 even when moments are STORED low
        # precision (bf16 accumulation would drift); rounded on store
        low = self.moment_dtype != jnp.float32
        m_in = jax.tree.map(lambda a: a.astype(jnp.float32),
                            state.moment1) if low else state.moment1
        v_in = jax.tree.map(lambda a: a.astype(jnp.float32),
                            state.moment2) if low else state.moment2

        if self.decay_mask is not None:
            triples = jax.tree.map(
                lambda w, g, m, v, dm: adamw_kernel(
                    w, g, m, v, t, lr=lr, b1=self.b1, b2=self.b2,
                    eps=self.eps, weight_decay=self.weight_decay,
                    do_decay=dm),
                params, grads, m_in, v_in, self.decay_mask)
        else:
            triples = jax.tree.map(
                lambda w, g, m, v: adamw_kernel(
                    w, g, m, v, t, lr=lr, b1=self.b1, b2=self.b2,
                    eps=self.eps, weight_decay=self.weight_decay),
                params, grads, m_in, v_in)
        new_params, new_m, new_v = jax.tree.transpose(
            jax.tree.structure(params), jax.tree.structure((0, 0, 0)),
            triples)
        if low:
            new_m = jax.tree.map(
                lambda a: a.astype(self.moment_dtype), new_m)
            new_v = jax.tree.map(
                lambda a: a.astype(self.moment_dtype), new_v)
        return new_params, AdamWState(new_m, new_v, count), norm
