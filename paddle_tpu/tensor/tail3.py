"""Tensor-API long tail, batch 3 (ref surface: python/paddle/tensor/ —
the paddle 3.x additions and the remaining generated in-place variants;
VERDICT r2 item 5).

Same contracts as tail.py: differentiable ops dispatch through
core.dispatch.apply; in-place ops rebind the buffer and refuse
grad-requiring tensors.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor
from . import linalg as _linalg
from . import logic as _logic
from . import manipulation as _manip
from . import math as _math
from . import tail as _tail

__all__ = [
    "binomial", "log_normal", "log_normal_", "reduce_as", "bernoulli_",
    "sinc_", "square_", "erf_", "i0_", "t_", "where_", "mod_",
    "floor_mod_", "addmm_",
    "equal_", "not_equal_", "greater_equal_", "greater_than_",
    "less_equal_", "less_than_",
    "logical_and_", "logical_or_", "logical_xor_", "logical_not_",
    "bitwise_and_", "bitwise_or_", "bitwise_xor_", "bitwise_not_",
    "bitwise_invert_",
]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


# ---------------------------------------------------------------------------
# new out-of-place ops
# ---------------------------------------------------------------------------
def binomial(count, prob, name=None):
    """Sample Binomial(count, prob) elementwise (ref: paddle.binomial)."""
    from ..framework.random import next_key
    n = _arr(count).astype(jnp.float32)
    p = _arr(prob).astype(jnp.float32)
    from ..core.dtypes import convert_dtype
    out = jax.random.binomial(next_key(), n, p)
    # "int64" demotes per the framework's x64 policy (core/dtypes.py)
    return Tensor(out.astype(convert_dtype("int64")))


def log_normal(mean=1.0, std=2.0, shape=None, dtype="float32", name=None):
    """exp(Normal(mean, std)) samples (ref: paddle.log_normal — note the
    mean/std parameterize the UNDERLYING normal, paddle semantics)."""
    from ..core.dtypes import convert_dtype
    from ..framework.random import next_key
    shp = tuple(shape) if shape is not None else ()
    dt = convert_dtype(dtype) or "float32"
    z = jax.random.normal(next_key(), shp, jnp.float32)
    return Tensor(jnp.exp(_arr(mean) + _arr(std) * z).astype(dt))


def reduce_as(x, target, name=None):
    """Sum-reduce x over the broadcast dims so its shape matches target
    (ref: paddle.reduce_as — the gradient-of-broadcast helper)."""
    tgt = _arr(target).shape

    def impl(a):
        extra = a.ndim - len(tgt)
        if extra:
            a = a.sum(axis=tuple(range(extra)))
        keep = tuple(i for i, (s, t) in enumerate(zip(a.shape, tgt))
                     if s != t)
        if keep:
            a = a.sum(axis=keep, keepdims=True)
        return a
    return apply("reduce_as", impl, [x])


# ---------------------------------------------------------------------------
# in-place family, batch 3
# ---------------------------------------------------------------------------
_guard_inplace = _tail._guard_inplace
_inplace_of = _tail._inplace_of


def bernoulli_(x, p=0.5, name=None):
    _guard_inplace(x, "bernoulli_")
    from ..framework.random import next_key
    pr = _arr(p) if isinstance(p, Tensor) else p
    x._data = jax.random.bernoulli(next_key(), pr, _arr(x).shape).astype(
        x.dtype)
    return x


def log_normal_(x, mean=1.0, std=2.0, name=None):
    _guard_inplace(x, "log_normal_")
    from ..framework.random import next_key
    z = jax.random.normal(next_key(), _arr(x).shape, jnp.float32)
    x._data = jnp.exp(_arr(mean) + _arr(std) * z).astype(x.dtype)
    return x


def t_(x, name=None):
    _guard_inplace(x, "t_")
    x._data = _linalg.t(Tensor(x._data))._data
    return x


def where_(condition, x, y, name=None):
    """In-place where: x keeps its value where condition, takes y
    elsewhere (ref: paddle.where_)."""
    _guard_inplace(x, "where_")
    x._data = jnp.where(_arr(condition), _arr(x), _arr(y))
    return x


def addmm_(input, x, y, beta=1.0, alpha=1.0, name=None):
    _guard_inplace(input, "addmm_")
    input._data = beta * _arr(input) + alpha * jnp.matmul(_arr(x), _arr(y))
    return input


sinc_ = _inplace_of(_math.sinc)
square_ = _inplace_of(_math.square)
erf_ = _inplace_of(_math.erf)
i0_ = _inplace_of(_math.i0)
mod_ = _inplace_of(_math.remainder)
floor_mod_ = _inplace_of(_math.remainder)
equal_ = _inplace_of(_logic.equal)
not_equal_ = _inplace_of(_logic.not_equal)
greater_equal_ = _inplace_of(_logic.greater_equal)
greater_than_ = _inplace_of(_logic.greater_than)
less_equal_ = _inplace_of(_logic.less_equal)
less_than_ = _inplace_of(_logic.less_than)
logical_and_ = _inplace_of(_logic.logical_and)
logical_or_ = _inplace_of(_logic.logical_or)
logical_xor_ = _inplace_of(_logic.logical_xor)
logical_not_ = _inplace_of(_logic.logical_not)
bitwise_and_ = _inplace_of(_logic.bitwise_and)
bitwise_or_ = _inplace_of(_logic.bitwise_or)
bitwise_xor_ = _inplace_of(_logic.bitwise_xor)
bitwise_not_ = _inplace_of(_logic.bitwise_not)
bitwise_invert_ = _inplace_of(_tail.bitwise_invert)
