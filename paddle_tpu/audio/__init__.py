"""paddle.audio parity (ref: python/paddle/audio/ — Spectrogram/MelSpectrogram
/MFCC features; SURVEY §2.2 misc numerics)."""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..signal import stft

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "compute_fbank_matrix",
           "Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def hz_to_mel(f, htk: bool = False):
    if htk:
        return 2595.0 * jnp.log10(1.0 + jnp.asarray(f) / 700.0)
    f = jnp.asarray(f, jnp.float32)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(f >= min_log_hz,
                     min_log_mel + jnp.log(f / min_log_hz) / logstep, mels)


def mel_to_hz(mel, htk: bool = False):
    if htk:
        return 700.0 * (10.0 ** (jnp.asarray(mel) / 2595.0) - 1.0)
    mel = jnp.asarray(mel, jnp.float32)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(mel >= min_log_mel,
                     min_log_hz * jnp.exp(logstep * (mel - min_log_mel)),
                     freqs)


def mel_frequencies(n_mels: int, f_min: float, f_max: float,
                    htk: bool = False):
    lo, hi = hz_to_mel(f_min, htk), hz_to_mel(f_max, htk)
    return mel_to_hz(jnp.linspace(lo, hi, n_mels), htk)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False):
    f_max = f_max or sr / 2
    fft_freqs = jnp.linspace(0, sr / 2, n_fft // 2 + 1)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fft_freqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0, jnp.minimum(lower, upper))
    enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
    return weights * enorm[:, None]


class Spectrogram:
    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect"):
        self.n_fft, self.hop = n_fft, hop_length or n_fft // 4
        self.win_length = win_length
        self.power, self.center, self.pad_mode = power, center, pad_mode

    def __call__(self, x):
        spec = stft(x, self.n_fft, self.hop, self.win_length,
                    center=self.center, pad_mode=self.pad_mode)
        sa = spec._data if isinstance(spec, Tensor) else spec
        return Tensor(jnp.abs(sa) ** self.power)


class MelSpectrogram:
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None, n_mels: int = 64,
                 f_min: float = 0.0, f_max: Optional[float] = None,
                 power: float = 2.0):
        self.spec = Spectrogram(n_fft, hop_length, power=power)
        self.fbank = compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max)

    def __call__(self, x):
        s = self.spec(x)._data                      # [..., freq, T]
        return Tensor(jnp.einsum("mf,...ft->...mt", self.fbank, s))


class LogMelSpectrogram(MelSpectrogram):
    def __init__(self, *a, ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, **kw):
        super().__init__(*a, **kw)
        self.amin, self.ref, self.top_db = amin, ref_value, top_db

    def __call__(self, x):
        m = super().__call__(x)._data
        log_m = 10.0 * jnp.log10(jnp.maximum(m, self.amin) / self.ref)
        if self.top_db is not None:
            log_m = jnp.maximum(log_m, jnp.max(log_m) - self.top_db)
        return Tensor(log_m)


def _dct_matrix(n_mfcc: int, n_mels: int):
    n = jnp.arange(n_mels)
    k = jnp.arange(n_mfcc)[:, None]
    dct = jnp.cos(math.pi / n_mels * (n + 0.5) * k) * math.sqrt(2.0 / n_mels)
    return dct.at[0].multiply(1.0 / math.sqrt(2))


class MFCC:
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_mels: int = 64,
                 n_fft: int = 512, **kw):
        self.logmel = LogMelSpectrogram(sr, n_fft, n_mels=n_mels, **kw)
        self.dct = _dct_matrix(n_mfcc, n_mels)

    def __call__(self, x):
        lm = self.logmel(x)._data                   # [..., mel, T]
        return Tensor(jnp.einsum("km,...mt->...kt", self.dct, lm))
